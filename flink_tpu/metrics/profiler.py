"""Device-time ledger: per-program dispatch profiling, recompile
attribution, and static cost-model accounting.

``DEVICE_STATS`` counts *events* (compiles, cache hits, transfer bytes)
but attributes no wall-clock device time to anything — the multi-query
device-time scheduler and the self-tuning controller on the ROADMAP both
need to know *which program, owned by which operator and job, is burning
the device*.  The process-global :data:`DEVICE_LEDGER` (same singleton +
``configure(config)`` pattern as ``DEVICE_STATS``/``TRACER``/``FAULTS``,
wired by every deploy path via ``profiler.*`` options) is that
measurement substrate.  It is OFF by default: a disabled ledger costs
one attribute read per dispatch site.

Every sample is attributed to a stable :class:`ProgramKey`::

    (job, operator, site, shape_signature)

* ``job``/``operator`` ride a thread-local dispatch context pushed by
  the operator chain at batch/watermark entry (``set_dispatch_context``)
  — dispatch sites themselves never know which job they serve.
* ``site`` is a dotted dispatch-site name from the doc-locked
  :data:`LEDGER_SITE_INVENTORY` (TPU305 keeps code, this inventory, and
  docs/OBSERVABILITY.md identical).
* ``shape_signature`` is the builder cache key of the dispatched
  program (``_TimedProgram._build_key`` / ``runtime.compiled.shape_key``)
  — already computed by the caches, so attribution adds no per-dispatch
  tree walk.

Each entry carries exact ``count``/``self_ms``/``compile_ms`` totals, a
bounded duration reservoir (p50/p95 percentile window; ``max`` is exact
over the entry's lifetime), EWMA duration + dispatch-rate estimates, and
— resolved lazily from PROGRAM_AUDIT at read time, never on the dispatch
path — a static roofline cost estimate traced from the program's jaxpr
(flop + byte counts, the Tier-B analyzer's walk):

    estimated_ms = max(flops / gflops, bytes / gbps)

with ``profiler.cost-model.gflops`` / ``profiler.cost-model.gbps`` as
the assumed rates; ``achieved_vs_estimated`` is measured/estimated.

Recompile attribution: on every instrumented-cache miss after a scope's
first build, the new builder arguments are diffed against the nearest
prior build (most shared parameters) and the record names exactly which
parameter — down to the tuple element, e.g. ``shape[1]: 64 -> 128`` —
changed.  ``recompiles != 0`` regressions become one CLI table
(``python -m flink_tpu.cli profile <job>``) instead of a grep hunt.

Durations are measured with ``time.perf_counter()`` and clamped to be
non-negative; timestamps come from the monotonic-anchored ``now_ms()``
(TPU501: no wall clock in span paths).  All mutation happens under one
ledger lock (TPU401); scrape paths copy under the same lock, so a
concurrent record/scrape drill sees no torn reads.
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from .tracing import now_ms

__all__ = [
    "ProgramKey", "DeviceLedger", "DEVICE_LEDGER",
    "LEDGER_SITE_INVENTORY", "bind_ledger_metrics",
    "set_dispatch_context", "clear_dispatch_context", "dispatch_context",
]


class ProgramKey(NamedTuple):
    """Stable attribution key for one profiled program."""

    job: str
    operator: str
    site: str
    shape_signature: str


# ---------------------------------------------------------------------------
# Thread-local dispatch context: the operator chain pushes (job, operator)
# at batch/watermark entry so device dispatch sites — which know only
# their site name — can attribute time to the owning job and operator.
# ---------------------------------------------------------------------------

_CTX = threading.local()


def set_dispatch_context(job: str, operator: str) -> None:
    """Pin the (job, operator) owner for ledger samples recorded on this
    thread until the next ``set_dispatch_context``/``clear``."""
    _CTX.job = job
    _CTX.operator = operator


def clear_dispatch_context() -> None:
    _CTX.job = ""
    _CTX.operator = ""


def dispatch_context() -> Tuple[str, str]:
    return (getattr(_CTX, "job", ""), getattr(_CTX, "operator", ""))


# ---------------------------------------------------------------------------
# Per-key ledger entries
# ---------------------------------------------------------------------------


class _Entry:
    """Mutable accumulator for one ProgramKey.  Mutated only under the
    owning ledger's lock — it carries no lock of its own."""

    __slots__ = ("count", "compiles", "self_ms", "compile_ms", "max_ms",
                 "ewma_ms", "ewma_interval_ms", "last_ts_ms", "nbytes",
                 "reservoir")

    def __init__(self, reservoir: int):
        self.count = 0              # dispatches (compile calls excluded)
        self.compiles = 0
        self.self_ms = 0.0          # device dispatch time
        self.compile_ms = 0.0       # trace/lower/compile time
        self.max_ms = 0.0           # exact lifetime max dispatch duration
        self.ewma_ms = 0.0
        self.ewma_interval_ms = 0.0
        self.last_ts_ms = 0
        self.nbytes = 0             # payload bytes (transfer sites)
        self.reservoir: deque = deque(maxlen=max(1, int(reservoir)))


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


# ---------------------------------------------------------------------------
# Static cost model: flop/byte counts traced from the program's jaxpr at
# its audited abstract signature (the Tier-B analyzer's recursive walk),
# folded through a two-term roofline.  Resolved lazily at READ time and
# cached per (site, shape_signature) — never on the dispatch path.
# ---------------------------------------------------------------------------


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                yield from _iter_eqns(sub)


def _sub_jaxprs(val):
    inner = getattr(val, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        yield inner
    elif hasattr(val, "eqns"):
        yield val
    elif isinstance(val, (tuple, list)):
        for v in val:
            yield from _sub_jaxprs(v)


def _aval_elems(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()) or ():
        n *= int(d)
    return n


def _aval_bytes(aval) -> int:
    try:
        import numpy as np
        return _aval_elems(aval) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _estimate_flops_bytes(closed) -> Tuple[int, int]:
    """(flops, bytes) of a ClosedJaxpr: one flop per output element per
    equation (elementwise model), 2*M*N*K for dot_general; bytes are the
    program's top-level input + output buffer footprint (what the
    dispatch actually moves through HBM at minimum)."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    flops = 0
    for eqn in _iter_eqns(jaxpr):
        out_elems = sum(_aval_elems(getattr(v, "aval", None) or ())
                        for v in eqn.outvars)
        if eqn.primitive.name == "dot_general":
            k = 1
            try:
                (contract, _batch) = eqn.params["dimension_numbers"]
                lhs = eqn.invars[0].aval
                for d in contract[0]:
                    k *= int(lhs.shape[d])
            except Exception:
                pass
            flops += 2 * out_elems * k
        else:
            flops += out_elems
    nbytes = 0
    for v in list(jaxpr.invars) + list(jaxpr.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            nbytes += _aval_bytes(aval)
    return flops, nbytes


def _trace_cost(site: str, shape_signature: str) -> Optional[Tuple[int, int]]:
    """Resolve (flops, bytes) for a profiled program by re-tracing its
    PROGRAM_AUDIT entry abstractly; None when no audit entry matches or
    the program cannot be abstractly re-traced."""
    try:
        import jax

        from .device import PROGRAM_AUDIT
    except Exception:
        return None
    for entry in list(PROGRAM_AUDIT):
        if entry.scope != site or entry.build_key != shape_signature:
            continue
        try:
            closed = jax.make_jaxpr(entry.fn)(*entry.abstract_args,
                                              **entry.abstract_kwargs)
        except Exception:
            return None
        return _estimate_flops_bytes(closed)
    return None


# ---------------------------------------------------------------------------
# Recompile attribution
# ---------------------------------------------------------------------------


def _bind_builder_args(builder, args: tuple, kwargs: dict) -> Dict[str, Any]:
    """Builder arguments by parameter name (repr-compared); positional
    fallback ``arg0``/``arg1``… when the signature cannot be bound."""
    try:
        bound = inspect.signature(builder).bind(*args, **kwargs)
        bound.apply_defaults()
        return dict(bound.arguments)
    except (TypeError, ValueError):
        named = {f"arg{i}": a for i, a in enumerate(args)}
        named.update(kwargs)
        return named


def _describe_changes(prev: Dict[str, Any],
                      cur: Dict[str, Any]) -> List[str]:
    """Human-readable per-parameter diff; tuples of equal length diff to
    the exact changed element (``shape[1]: 64 -> 128``)."""
    changed: List[str] = []
    for name in sorted(set(prev) | set(cur)):
        if name not in prev:
            changed.append(f"{name}: <absent> -> {cur[name]!r}")
            continue
        if name not in cur:
            changed.append(f"{name}: {prev[name]!r} -> <absent>")
            continue
        old, new = prev[name], cur[name]
        if repr(old) == repr(new):
            continue
        if (isinstance(old, tuple) and isinstance(new, tuple)
                and len(old) == len(new)):
            for i, (a, b) in enumerate(zip(old, new)):
                if repr(a) != repr(b):
                    changed.append(f"{name}[{i}]: {a!r} -> {b!r}")
        else:
            changed.append(f"{name}: {old!r} -> {new!r}")
    return changed


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


class DeviceLedger:
    """Process-global device-time ledger.  All mutation under one lock;
    every read surface copies under the same lock (no torn reads on the
    scrape path).  Disabled, every site pays one attribute read."""

    # Priors retained per site for nearest-prior recompile diffing.
    _PRIORS_PER_SITE = 8

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.reservoir = 256
        self.recompile_history = 64
        self.ewma_alpha = 0.2
        self.trace_samples = 2048
        self.cost_gflops = 50.0
        self.cost_gbps = 10.0
        self._entries: Dict[ProgramKey, _Entry] = {}
        self._builds: Dict[str, deque] = {}       # site -> prior builds
        self._recompiles: deque = deque(maxlen=64)
        self._samples: deque = deque(maxlen=2048)  # (ts_ms, site, ms)
        self._cost_cache: Dict[Tuple[str, str], Optional[Tuple[int, int]]] \
            = {}

    # -- wiring ------------------------------------------------------------

    def configure(self, config) -> None:
        """Apply ``profiler.*`` options (same pattern as FAULTS /
        WATCHDOG / TRACER); called by every deploy path."""
        from ..core.config import ProfilerOptions
        with self._lock:
            self.enabled = bool(config.get(ProfilerOptions.ENABLED))
            self.reservoir = int(config.get(ProfilerOptions.RESERVOIR))
            self.recompile_history = int(
                config.get(ProfilerOptions.RECOMPILE_HISTORY))
            self.ewma_alpha = float(config.get(ProfilerOptions.EWMA_ALPHA))
            self.trace_samples = int(
                config.get(ProfilerOptions.TRACE_SAMPLES))
            self.cost_gflops = float(
                config.get(ProfilerOptions.COST_GFLOPS))
            self.cost_gbps = float(config.get(ProfilerOptions.COST_GBPS))
            if self._recompiles.maxlen != self.recompile_history:
                self._recompiles = deque(
                    self._recompiles, maxlen=max(1, self.recompile_history))
            if self._samples.maxlen != self.trace_samples:
                self._samples = deque(
                    self._samples, maxlen=max(1, self.trace_samples))

    # -- recording (the dispatch path) -------------------------------------

    def record(self, site: str, ms: float, *, shape_sig: str = "",
               kind: str = "dispatch", nbytes: int = 0,
               job: Optional[str] = None,
               operator: Optional[str] = None) -> None:
        """Account one timed event at ``site``.  ``kind="compile"``
        charges trace/lower/compile time (a program's first dispatch);
        ``kind="dispatch"`` charges steady-state device time.  Durations
        are clamped non-negative (caller clock skew must never produce a
        negative total)."""
        if not self.enabled:
            return
        ms = max(float(ms), 0.0)
        if job is None or operator is None:
            cj, co = dispatch_context()
            job = cj if job is None else job
            operator = co if operator is None else operator
        key = ProgramKey(job, operator, site, shape_sig)
        ts = now_ms()
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = _Entry(self.reservoir)
            if kind == "compile":
                e.compiles += 1
                e.compile_ms += ms
            else:
                e.count += 1
                e.self_ms += ms
                e.nbytes += int(nbytes)
                if ms > e.max_ms:
                    e.max_ms = ms
                e.reservoir.append(ms)
                a = self.ewma_alpha
                e.ewma_ms = ms if e.count == 1 \
                    else (1.0 - a) * e.ewma_ms + a * ms
                if e.last_ts_ms:
                    dt = max(ts - e.last_ts_ms, 0)
                    e.ewma_interval_ms = dt if e.ewma_interval_ms == 0.0 \
                        else (1.0 - a) * e.ewma_interval_ms + a * dt
                e.last_ts_ms = ts
            self._samples.append((ts, site, ms))

    def note_build(self, site: str, build_key: str, builder,
                   args: tuple, kwargs: dict) -> None:
        """Recompile attribution: called on every instrumented-cache
        MISS.  The first build of a site is the expected compile; each
        later build is diffed parameter-by-parameter against the nearest
        prior build (most shared arguments) and the record names exactly
        which dimension changed.  Never counted into
        ``DEVICE_STATS.compiles`` — the bench recompile budget is not
        this ledger's to spend."""
        if not self.enabled:
            return
        named = _bind_builder_args(builder, args, kwargs)
        job, operator = dispatch_context()
        with self._lock:
            priors = self._builds.get(site)
            if priors is None:
                priors = self._builds[site] = deque(
                    maxlen=self._PRIORS_PER_SITE)
            record = None
            if priors:
                def shared(p):
                    return sum(1 for k, v in p[1].items()
                               if k in named and repr(named[k]) == repr(v))
                nearest = max(priors, key=shared)
                record = {
                    "site": site, "job": job, "operator": operator,
                    "key": build_key, "prior_key": nearest[0],
                    "changed": _describe_changes(nearest[1], named),
                    "ts_ms": now_ms(),
                }
            priors.append((build_key, named))
            if record is not None:
                self._recompiles.append(record)

    # -- read surfaces -----------------------------------------------------

    def _cost_for(self, site: str,
                  shape_signature: str) -> Optional[Tuple[int, int]]:
        # lazy + cached: jaxpr re-tracing is read-path work only
        ck = (site, shape_signature)
        with self._lock:
            if ck in self._cost_cache:
                return self._cost_cache[ck]
        cost = _trace_cost(site, shape_signature) if shape_signature else None
        with self._lock:
            self._cost_cache[ck] = cost
        return cost

    def _entry_dict(self, key: ProgramKey, e: _Entry,
                    window: List[float]) -> dict:
        window.sort()
        mean = e.self_ms / e.count if e.count else 0.0
        rate = (1000.0 / e.ewma_interval_ms
                if e.ewma_interval_ms > 0.0 else 0.0)
        return {
            "job": key.job, "operator": key.operator, "site": key.site,
            "shape_signature": key.shape_signature,
            "count": e.count, "compiles": e.compiles,
            "self_ms": e.self_ms, "compile_ms": e.compile_ms,
            "total_ms": e.self_ms + e.compile_ms,
            "mean_ms": mean, "p50_ms": _percentile(window, 0.50),
            "p95_ms": _percentile(window, 0.95), "max_ms": e.max_ms,
            "ewma_ms": e.ewma_ms, "rate_hz": rate, "bytes": e.nbytes,
        }

    def _with_cost(self, d: dict) -> dict:
        """Attach the static cost estimate to an entry dict."""
        cost = self._cost_for(d["site"], d["shape_signature"])
        if cost is None and d["site"].startswith("transfer.") and d["bytes"]:
            # transfers have no jaxpr; the byte term IS the model
            cost = (0, d["bytes"] // max(d["count"], 1))
        if cost is None:
            d.update(est_flops=None, est_bytes=None, est_ms=None,
                     achieved_vs_estimated=None)
            return d
        flops, nbytes = cost
        est_ms = max(flops / (self.cost_gflops * 1e6),
                     nbytes / (self.cost_gbps * 1e6))
        d.update(est_flops=flops, est_bytes=nbytes, est_ms=est_ms,
                 achieved_vs_estimated=(
                     d["mean_ms"] / est_ms if est_ms > 0.0 else None))
        return d

    def snapshot(self) -> dict:
        """Cheap rollups for /metrics and prometheus: totals plus
        per-job and per-site device-time shares.  No jaxpr work."""
        with self._lock:
            items = [(k, e, list(e.reservoir))
                     for k, e in self._entries.items()]
            recompiles = len(self._recompiles)
        jobs: Dict[str, dict] = {}
        sites: Dict[str, dict] = {}
        operators: Dict[str, dict] = {}
        tot_self = tot_compile = 0.0
        tot_count = 0
        for key, e, _w in items:
            tot_self += e.self_ms
            tot_compile += e.compile_ms
            tot_count += e.count
            j = jobs.setdefault(key.job or "<unattributed>",
                                {"device_ms": 0.0, "compile_ms": 0.0,
                                 "dispatches": 0})
            j["device_ms"] += e.self_ms
            j["compile_ms"] += e.compile_ms
            j["dispatches"] += e.count
            s = sites.setdefault(key.site, {"device_ms": 0.0, "count": 0})
            s["device_ms"] += e.self_ms
            s["count"] += e.count
            o = operators.setdefault(key.operator or "<unattributed>",
                                     {"device_ms": 0.0, "count": 0})
            o["device_ms"] += e.self_ms
            o["count"] += e.count
        return {
            "enabled": self.enabled, "entries": len(items),
            "device_ms_total": tot_self, "compile_ms_total": tot_compile,
            "dispatches_total": tot_count,
            "recompiles_attributed": recompiles,
            "jobs": jobs, "sites": sites, "operators": operators,
        }

    def profile(self, job: Optional[str] = None, top: int = 10) -> dict:
        """The full attribution report: top-``top`` hot programs (cost
        model attached), per-operator device-time shares, and the
        recompile-attribution records.  ``job`` filters by exact job
        name; None aggregates every job."""
        with self._lock:
            items = [(k, e, list(e.reservoir))
                     for k, e in self._entries.items()]
            recompiles = [dict(r) for r in self._recompiles]
        if job is not None:
            items = [(k, e, w) for k, e, w in items if k.job == job]
            recompiles = [r for r in recompiles if r.get("job") == job]
        rows = [self._entry_dict(k, e, w) for k, e, w in items]
        total_self = sum(r["self_ms"] for r in rows)
        total_compile = sum(r["compile_ms"] for r in rows)
        for r in rows:
            r["share"] = (r["self_ms"] / total_self) if total_self else 0.0
        rows.sort(key=lambda r: (-r["total_ms"], r["site"],
                                 r["shape_signature"]))
        operators: Dict[str, float] = {}
        for r in rows:
            op = r["operator"] or "<unattributed>"
            operators[op] = operators.get(op, 0.0) + r["self_ms"]
        op_rows = [{"operator": op, "device_ms": ms,
                    "share": (ms / total_self) if total_self else 0.0}
                   for op, ms in sorted(operators.items(),
                                        key=lambda kv: -kv[1])]
        return {
            "job": job, "enabled": self.enabled,
            "total_device_ms": total_self,
            "total_compile_ms": total_compile,
            "programs": [self._with_cost(r) for r in rows[:max(0, top)]],
            "operators": op_rows,
            "recompiles": recompiles,
        }

    def trace_counters(self) -> List[dict]:
        """Recent (ts_ms, site, ms) samples for the Perfetto counter
        tracks (``chrome_trace_events(counters=...)``)."""
        with self._lock:
            return [{"ts_ms": ts, "site": site, "ms": ms}
                    for ts, site, ms in self._samples]

    def reset(self) -> None:
        """Test hook: drop every entry, prior build, and sample."""
        with self._lock:
            self._entries.clear()
            self._builds.clear()
            self._recompiles.clear()
            self._samples.clear()
            self._cost_cache.clear()


DEVICE_LEDGER = DeviceLedger()


def bind_ledger_metrics(registry) -> None:
    """Register ledger rollups as gauges under the ``profiler`` scope of
    a MetricRegistry (prometheus: ``flink_tpu_profiler_*``).  Idempotent:
    re-binding overwrites the same scope entries."""
    g = registry.root().group("profiler")
    led = DEVICE_LEDGER
    g.gauge("enabled", lambda: 1 if led.enabled else 0)
    g.gauge("entries", lambda: led.snapshot()["entries"])
    g.gauge("device_ms_total",
            lambda: led.snapshot()["device_ms_total"])
    g.gauge("compile_ms_total",
            lambda: led.snapshot()["compile_ms_total"])
    g.gauge("dispatches_total",
            lambda: led.snapshot()["dispatches_total"])
    g.gauge("recompiles_attributed_total",
            lambda: led.snapshot()["recompiles_attributed"])


# Every ledger dispatch site, with its recording location.  The
# "Device-time ledger" section of docs/OBSERVABILITY.md renders this
# inventory as a table and TPU305 asserts code literals (every
# ``instrumented_program_cache("<site>")`` builder and every literal
# ``DEVICE_LEDGER.record("<site>", ...)`` call), this tuple, and the doc
# table stay identical.  Keep entries sorted by site.
LEDGER_SITE_INVENTORY: tuple = (
    ("chain.fused_prelude",
     "runtime/compiled.py FusedChain.run — certified decode prelude "
     "registration (compile marker; its time is charged to the fused "
     "step that contains it)"),
    ("chain.fused_step",
     "runtime/compiled.py FusedChain.run — one fused decode+step "
     "dispatch per certified micro-batch"),
    ("device_session.fire",
     "runtime/operators/device_session.py — session-window fire "
     "(merge + emit) program"),
    ("device_session.step",
     "runtime/operators/device_session.py — per-batch session ingest "
     "program"),
    ("device_window.fire",
     "runtime/operators/device_window.py — full pane fire program"),
    ("device_window.fire_inc",
     "runtime/operators/device_window.py — incremental fire merge "
     "program"),
    ("device_window.fire_rebuild",
     "runtime/operators/device_window.py — post-fire table rebuild "
     "program"),
    ("device_window.native_fold",
     "runtime/operators/device_window.py — coalesced multi-batch "
     "device-ingest fold"),
    ("device_window.seal",
     "runtime/operators/device_window.py — pane seal program "
     "(incremental fire engine)"),
    ("device_window.step",
     "runtime/operators/device_window.py — per-batch window ingest "
     "program"),
    ("mesh.fire",  # lint: key-ok ledger site, not a config key
     "parallel/sharded_window.py — sharded fire (compact) program"),
    ("mesh.fire_full",
     "parallel/sharded_window.py — sharded full-fire program"),
    ("mesh.fire_inc",
     "parallel/sharded_window.py — sharded incremental fire program"),
    ("mesh.rebuild_inc",
     "parallel/sharded_window.py — sharded incremental rebuild "
     "program"),
    ("mesh.retire",  # lint: key-ok ledger site, not a config key
     "parallel/sharded_window.py — retired-pane cleanup program"),
    ("mesh.seal_inc",
     "parallel/sharded_window.py — sharded pane seal program"),
    ("mesh.step",  # lint: key-ok ledger site, not a config key
     "parallel/sharded_window.py — sharded per-batch ingest program"),
    ("ops.pallas_topk",
     "ops/pallas_topk.py — top-k selection kernel"),
    ("sched.throttle",  # lint: key-ok ledger site, not a config key
     "runtime/stream_task.py _admission_gate — wall time a micro-batch "
     "waited at the per-job admission gate before dispatch (quota "
     "pressure, charged to the throttled job)"),
    ("sql.device_group_agg",
     "sql/device_group_agg.py — SQL grouped-aggregation program"),
    ("state.reset_row",
     "state/tpu_backend.py — keyed-state row reset program"),
    ("transfer.d2h",
     "metrics/device.py note_d2h — device→host transfer"),
    ("transfer.h2d",
     "metrics/device.py note_h2d — host→device transfer"),
)
