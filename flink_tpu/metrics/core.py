"""Metrics: counters/gauges/meters/histograms in scoped groups.

Analog of flink-metrics-core (MetricGroup.java:36, Counter/Gauge/Histogram/
Meter) and the runtime registry (MetricRegistryImpl.java:74) with scoped
groups per job/task/operator. Reporters (metrics/reporters.py) poll the
registry on an interval, like the reference's reporter setup.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

__all__ = ["Counter", "Gauge", "Meter", "Histogram", "MetricGroup",
           "MetricRegistry", "TaskMetrics"]


class Counter:
    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    def dec(self, n: int = 1) -> None:
        self._value -= n

    @property
    def count(self) -> int:
        return self._value


class Gauge:
    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn

    @property
    def value(self) -> Any:
        return self._fn()


class Meter:
    """Rate over a sliding minute (reference MeterView)."""

    def __init__(self):
        self._events: deque[tuple[float, int]] = deque()
        self._count = 0

    def mark(self, n: int = 1) -> None:
        self._count += n
        now = time.time()
        self._events.append((now, n))
        cutoff = now - 60.0
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    @property
    def rate(self) -> float:
        now = time.time()
        recent = sum(n for t, n in self._events if t >= now - 60.0)
        return recent / 60.0

    @property
    def count(self) -> int:
        return self._count


class Histogram:
    """Reservoir histogram with quantiles."""

    def __init__(self, window: int = 1024):
        self._values: deque[float] = deque(maxlen=window)

    def update(self, value: float) -> None:
        self._values.append(float(value))

    def quantile(self, q: float) -> float:
        if not self._values:
            return 0.0
        vals = sorted(self._values)
        idx = min(int(q * len(vals)), len(vals) - 1)
        return vals[idx]

    @property
    def count(self) -> int:
        return len(self._values)

    @property
    def mean(self) -> float:
        return sum(self._values) / len(self._values) if self._values else 0.0


class MetricGroup:
    """Hierarchical scope: registry.group('job').group('task')..."""

    def __init__(self, registry: "MetricRegistry", scope: tuple[str, ...]):
        self._registry = registry
        self.scope = scope

    def group(self, name: str) -> "MetricGroup":
        return MetricGroup(self._registry, self.scope + (name,))

    def _register(self, name: str, metric) -> Any:
        self._registry.register(self.scope + (name,), metric)
        return metric

    def counter(self, name: str) -> Counter:
        return self._register(name, Counter())

    def gauge(self, name: str, fn: Callable[[], Any]) -> Gauge:
        return self._register(name, Gauge(fn))

    def meter(self, name: str) -> Meter:
        return self._register(name, Meter())

    def histogram(self, name: str, window: int = 1024) -> Histogram:
        return self._register(name, Histogram(window))


class MetricRegistry:
    def __init__(self):
        self._metrics: dict[tuple[str, ...], Any] = {}
        self._lock = threading.Lock()

    def register(self, scope: tuple[str, ...], metric) -> None:
        with self._lock:
            self._metrics[scope] = metric

    def root(self) -> MetricGroup:
        return MetricGroup(self, ())

    def all_metrics(self) -> dict[tuple[str, ...], Any]:
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> dict[str, Any]:
        """Flat name -> numeric value view for reporters."""
        out: dict[str, Any] = {}
        for scope, m in self.all_metrics().items():
            name = ".".join(scope)
            if isinstance(m, Counter):
                out[name] = m.count
            elif isinstance(m, Gauge):
                try:
                    out[name] = m.value
                except Exception:  # noqa: BLE001 - gauge fn may race shutdown
                    out[name] = None
            elif isinstance(m, Meter):
                out[name + ".rate"] = m.rate
                out[name + ".count"] = m.count
            elif isinstance(m, Histogram):
                out[name + ".p50"] = m.quantile(0.50)
                out[name + ".p99"] = m.quantile(0.99)
                out[name + ".mean"] = m.mean
        return out


class TaskMetrics:
    """Standard per-task IO metrics (reference numRecordsIn/Out,
    busy/backpressure gauges)."""

    def __init__(self, registry: MetricRegistry, job: str, vertex: str,
                 subtask: int):
        g = registry.root().group(job).group(vertex).group(str(subtask))
        self.records_in = g.counter("numRecordsIn")
        self.records_out = g.counter("numRecordsOut")
        self.watermark_lag = g.histogram("watermarkLag")
        self.batch_size = g.histogram("batchSize")
        self.group = g
