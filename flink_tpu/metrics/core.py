"""Metrics: counters/gauges/meters/histograms in scoped groups.

Analog of flink-metrics-core (MetricGroup.java:36, Counter/Gauge/Histogram/
Meter) and the runtime registry (MetricRegistryImpl.java:74) with scoped
groups per job/task/operator. Reporters (metrics/reporters.py) poll the
registry on an interval, like the reference's reporter setup.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

__all__ = ["Counter", "Gauge", "Meter", "Histogram", "MetricGroup",
           "MetricRegistry", "TaskMetrics"]


class Counter:
    """Thread-safe counter: reporters poll from their own thread while the
    mailbox loop mutates, and ``_value += n`` is a read-modify-write the
    GIL does not make atomic (reference SimpleCounter is single-writer;
    here the lock keeps multi-writer updates lossless too)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: int = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def count(self) -> int:
        return self._value


class Gauge:
    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn

    @property
    def value(self) -> Any:
        return self._fn()


class Meter:
    """Rate over a sliding minute (reference MeterView). Locked: the
    reporter thread iterates the event window while the task thread
    appends/evicts — unsynchronized, that's a lost update on ``_count``
    and a RuntimeError-free but torn read of the deque."""

    def __init__(self):
        self._events: deque[tuple[float, int]] = deque()
        self._count = 0
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        now = time.time()
        with self._lock:
            self._count += n
            self._events.append((now, n))
            cutoff = now - 60.0
            while self._events and self._events[0][0] < cutoff:
                self._events.popleft()

    @property
    def rate(self) -> float:
        now = time.time()
        with self._lock:
            recent = sum(n for t, n in self._events if t >= now - 60.0)
        return recent / 60.0

    @property
    def count(self) -> int:
        return self._count


class Histogram:
    """Reservoir histogram with quantiles. Locked for the same reason as
    Meter: ``sorted()`` over the deque while the owning thread appends
    past ``maxlen`` raises 'deque mutated during iteration'."""

    def __init__(self, window: int = 1024):
        self._values: deque[float] = deque(maxlen=window)
        self._lock = threading.Lock()

    def update(self, value: float) -> None:
        with self._lock:
            self._values.append(float(value))

    def quantile(self, q: float) -> float:
        with self._lock:
            vals = sorted(self._values)
        if not vals:
            return 0.0
        idx = min(int(q * len(vals)), len(vals) - 1)
        return vals[idx]

    # Default le-bounds for the cumulative exposition buckets: latency
    # histograms here are milliseconds, so a 1ms..10s log-ish ladder.
    BUCKET_BOUNDS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                     500.0, 1000.0, 2500.0, 5000.0, 10000.0)

    def bucket_counts(self, bounds: Optional[tuple] = None) \
            -> list[tuple[str, int]]:
        """Cumulative ``le``-labeled bucket counts over the reservoir
        window, ending with ``("+Inf", count)`` — what the Prometheus
        histogram exposition needs so external scrapers can aggregate
        across processes (summary quantiles cannot be aggregated)."""
        use = self.BUCKET_BOUNDS if bounds is None else tuple(bounds)
        with self._lock:
            vals = list(self._values)
        out: list[tuple[str, int]] = []
        for b in use:
            out.append((repr(float(b)), sum(1 for v in vals if v <= b)))
        out.append(("+Inf", len(vals)))
        return out

    @property
    def count(self) -> int:
        return len(self._values)

    @property
    def mean(self) -> float:
        with self._lock:
            vals = list(self._values)
        return sum(vals) / len(vals) if vals else 0.0

    @property
    def sum(self) -> float:
        with self._lock:
            return float(sum(self._values))


class MetricGroup:
    """Hierarchical scope: registry.group('job').group('task')..."""

    def __init__(self, registry: "MetricRegistry", scope: tuple[str, ...]):
        self._registry = registry
        self.scope = scope

    def group(self, name: str) -> "MetricGroup":
        return MetricGroup(self._registry, self.scope + (name,))

    def _register(self, name: str, metric) -> Any:
        self._registry.register(self.scope + (name,), metric)
        return metric

    def counter(self, name: str) -> Counter:
        return self._register(name, Counter())

    def gauge(self, name: str, fn: Callable[[], Any]) -> Gauge:
        return self._register(name, Gauge(fn))

    def meter(self, name: str) -> Meter:
        return self._register(name, Meter())

    def histogram(self, name: str, window: int = 1024) -> Histogram:
        return self._register(name, Histogram(window))


class MetricRegistry:
    def __init__(self):
        self._metrics: dict[tuple[str, ...], Any] = {}
        self._lock = threading.Lock()

    def register(self, scope: tuple[str, ...], metric) -> None:
        with self._lock:
            self._metrics[scope] = metric

    def root(self) -> MetricGroup:
        return MetricGroup(self, ())

    def all_metrics(self) -> dict[tuple[str, ...], Any]:
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> dict[str, Any]:
        """Flat name -> numeric value view for reporters."""
        out: dict[str, Any] = {}
        for scope, m in self.all_metrics().items():
            name = ".".join(scope)
            if isinstance(m, Counter):
                out[name] = m.count
            elif isinstance(m, Gauge):
                try:
                    out[name] = m.value
                except Exception:  # noqa: BLE001 - gauge fn may race shutdown
                    out[name] = None
            elif isinstance(m, Meter):
                out[name + ".rate"] = m.rate
                out[name + ".count"] = m.count
            elif isinstance(m, Histogram):
                out[name + ".p50"] = m.quantile(0.50)
                out[name + ".p99"] = m.quantile(0.99)
                out[name + ".mean"] = m.mean
        return out


class TaskMetrics:
    """Standard per-task IO metrics (reference numRecordsIn/Out,
    busy/backpressure gauges)."""

    def __init__(self, registry: MetricRegistry, job: str, vertex: str,
                 subtask: int):
        g = registry.root().group(job).group(vertex).group(str(subtask))
        self.records_in = g.counter("numRecordsIn")
        self.records_out = g.counter("numRecordsOut")
        self.watermark_lag = g.histogram("watermarkLag")
        self.batch_size = g.histogram("batchSize")
        self.group = g
        self.io_timers = None

    def bind_io_timers(self, timers) -> None:
        """Expose a task's busy/idle/backpressured accounting as gauges
        (reference TaskIOMetricGroup busyTimeMsPerSecond family). The
        timers object outlives the task thread, so reporters keep a
        stable terminal reading after the job finishes."""
        self.io_timers = timers
        g = self.group
        g.gauge("busyTimeMsPerSecond", lambda: timers.busy_ms_per_s)
        g.gauge("idleTimeMsPerSecond", lambda: timers.idle_ms_per_s)
        g.gauge("backPressuredTimeMsPerSecond",
                lambda: timers.backpressured_ms_per_s)
        g.gauge("busyTimeRatio", lambda: timers.busy_ratio)

    def bind_progress(self, progress) -> None:
        """Expose the task's progress-epoch age as a gauge
        (``lastProgressAgeMs``) — the per-task stall-supervision surface
        the detector, REST snapshot, and dashboards all read."""
        g = self.group
        g.gauge("lastProgressAgeMs", lambda: progress.age_ms)
        g.gauge("progressEpoch", lambda: progress.epoch)

    def operator_group(self, op_key: str) -> MetricGroup:
        """Per-operator scope under this task (WatermarkGauge / operator
        latency live here)."""
        return self.group.group(op_key)
