"""Tracing: spans for checkpoint/recovery/job phases.

Analog of the reference's 1.19 trace API (flink-metrics-core
traces/{Span.java, SpanBuilder.java:27, reporter/TraceReporter.java:31},
wired by TraceReporterSetup.java:63; checkpoint/recovery durations emitted
from CheckpointStatsTracker.java:267). Spans are scoped named durations with
attributes; reporters receive completed spans.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

__all__ = ["Span", "SpanBuilder", "TraceReporter", "InMemoryTraceReporter",
           "Tracer"]


@dataclass(frozen=True)
class Span:
    scope: str
    name: str
    start_ms: int
    end_ms: int
    attributes: dict = field(default_factory=dict)

    @property
    def duration_ms(self) -> int:
        return self.end_ms - self.start_ms


class SpanBuilder:
    """Fluent builder (reference SpanBuilder)."""

    def __init__(self, tracer: "Tracer", scope: str, name: str):
        self._tracer = tracer
        self._scope = scope
        self._name = name
        self._start_ms = int(time.time() * 1000)
        self._attrs: dict = {}

    def set_attribute(self, key: str, value: Any) -> "SpanBuilder":
        self._attrs[key] = value
        return self

    def set_start_ts(self, start_ms: int) -> "SpanBuilder":
        self._start_ms = int(start_ms)
        return self

    def finish(self, end_ms: Optional[int] = None) -> Span:
        span = Span(self._scope, self._name, self._start_ms,
                    int(time.time() * 1000) if end_ms is None else end_ms,
                    dict(self._attrs))
        self._tracer._report(span)
        return span

    def __enter__(self) -> "SpanBuilder":
        self._start_ms = int(time.time() * 1000)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.set_attribute("error", exc_type is not None)
        self.finish()


class TraceReporter:
    """Receives completed spans (reference TraceReporter.addSpan)."""

    def add_span(self, span: Span) -> None:
        raise NotImplementedError


class InMemoryTraceReporter(TraceReporter):
    def __init__(self):
        self.spans: list[Span] = []
        self._lock = threading.Lock()

    def add_span(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def by_name(self, name: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]


class Tracer:
    """Span factory + reporter fan-out (reference TraceReporterSetup)."""

    def __init__(self, reporters: Optional[list[TraceReporter]] = None):
        self._reporters = list(reporters or [])

    def add_reporter(self, reporter: TraceReporter) -> None:
        self._reporters.append(reporter)

    def span(self, scope: str, name: str) -> SpanBuilder:
        return SpanBuilder(self, scope, name)

    def _report(self, span: Span) -> None:
        for r in self._reporters:
            try:
                r.add_span(span)
            except Exception:  # noqa: BLE001 - reporters must not kill jobs
                pass
