"""Job-wide causal tracing + failure flight recorder.

Analog of the reference trace API (flink-metrics-core
traces/{Span.java, SpanBuilder.java:27, reporter/TraceReporter.java:31},
wired by TraceReporterSetup.java:63; checkpoint/recovery durations emitted
from CheckpointStatsTracker.java:267), grown into a causal tracing
subsystem: every span carries ``trace_id``/``span_id``/``parent_id`` so
related work — a checkpoint's trigger → per-subtask barrier alignment →
snapshot → artifact store → ack → complete fan-out — forms one tree even
when the pieces run on different hosts. A :class:`TraceContext` is the
wire-portable (trace_id, span_id) pair; it crosses process boundaries on
``CheckpointBarrier.trace`` and the distributed control messages, and
crosses thread boundaries via an explicit ``parent=`` argument or the
thread-local ambient context pushed by ``with tracer.span(...)``.

Clocks: span timestamps are *reported* as epoch milliseconds (the
reference Span contract) but *measured* on the monotonic clock — the
epoch offset is sampled once at import and added to ``time.monotonic()``
— so a wall-clock step (NTP slew, manual date change) can never produce
a negative ``duration_ms``.

Reporters are pluggable (:class:`TraceReporter`): a bounded in-memory
ring for REST/CLI inspection, a Chrome trace-event (Perfetto-loadable)
exporter (:func:`chrome_trace_events`), and the always-on
:class:`FlightRecorder` — a process-global bounded ring of recent
spans/events dumped to a timestamped JSON file whenever a fault
chokepoint fires (StallError, region restart, CorruptArtifactError,
zombie fence), turning every fault-injection drill into a readable
post-mortem.

The process-global :data:`TRACER` follows the same singleton +
``configure(config)`` pattern as ``FAULTS``/``WATCHDOG`` and is wired on
by every deploy path (local ``run_job``, ``JobSupervisor``, distributed
coordinator/worker).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "Span", "SpanBuilder", "TraceContext", "TraceReporter",
    "InMemoryTraceReporter", "FlightRecorder", "Tracer",
    "TRACER", "FLIGHT_RECORDER", "chrome_trace_events",
    "current_context", "use_context", "now_ms",
    "record_flight_event", "dump_flight_recorder", "SPAN_INVENTORY",
]

# Epoch offset sampled once at import: now_ms() is monotonic-derived but
# reports epoch milliseconds, so durations are immune to wall-clock steps
# while start times still line up with log timestamps.
_EPOCH_OFFSET_MS = time.time() * 1000.0 - time.monotonic() * 1000.0  # lint: wall-clock-ok sampled ONCE at import to anchor the monotonic clock


def now_ms() -> int:
    """Epoch milliseconds measured on the monotonic clock."""
    return int(time.monotonic() * 1000.0 + _EPOCH_OFFSET_MS)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """Wire-portable causal context: the (trace_id, span_id) a child span
    parents itself on. ``to_wire()`` produces a plain dict safe to embed
    in pickled control messages and ``CheckpointBarrier.trace``."""

    trace_id: str
    span_id: str

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_wire(d: Optional[dict]) -> Optional["TraceContext"]:
        if not d:
            return None
        try:
            return TraceContext(str(d["trace_id"]), str(d["span_id"]))
        except Exception:
            return None


@dataclass(frozen=True)
class Span:
    scope: str
    name: str
    start_ms: int
    end_ms: int
    attributes: dict = field(default_factory=dict)
    trace_id: str = ""
    span_id: str = ""
    parent_id: str = ""

    @property
    def duration_ms(self) -> int:
        return self.end_ms - self.start_ms

    def to_dict(self) -> dict:
        return {
            "scope": self.scope, "name": self.name,
            "start_ms": self.start_ms, "end_ms": self.end_ms,
            "duration_ms": self.duration_ms,
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id,
            "attributes": dict(self.attributes),
        }


# ---------------------------------------------------------------------------
# Ambient context: a thread-local stack so nested ``with tracer.span(...)``
# blocks parent automatically without threading a context argument through
# every call. Cross-thread/cross-host propagation stays explicit (parent=).
# ---------------------------------------------------------------------------

_TLS = threading.local()


def current_context() -> Optional[TraceContext]:
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


class use_context:
    """Pin ``ctx`` as the ambient parent for spans started on this thread
    inside the block (mailbox threads adopt the coordinator's checkpoint
    context carried on a barrier this way)."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        stack.append(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> None:
        _TLS.stack.pop()


class SpanBuilder:
    """Fluent builder (reference SpanBuilder). Usable imperatively
    (``b = tracer.span(...); ...; b.finish()``) or as a context manager —
    entering resets the start timestamp and pushes this span's context as
    the ambient parent for children started inside the block."""

    def __init__(self, tracer: "Tracer", scope: str, name: str,
                 parent: Optional[TraceContext] = None):
        self._tracer = tracer
        self._scope = scope
        self._name = name
        self._start_ms = now_ms()
        self._attrs: dict = {}
        if parent is None:
            parent = current_context()
        self._trace_id = parent.trace_id if parent else _new_id()
        self._span_id = _new_id()
        self._parent_id = parent.span_id if parent else ""
        self._finished = False
        self._ctx_cm: Optional[use_context] = None

    @property
    def context(self) -> TraceContext:
        """This span's identity, for parenting children (possibly on
        another host) before the span itself finishes."""
        return TraceContext(self._trace_id, self._span_id)

    def set_parent(self, ctx: Optional[TraceContext]) -> "SpanBuilder":
        if ctx is not None:
            self._trace_id = ctx.trace_id
            self._parent_id = ctx.span_id
        return self

    def set_attribute(self, key: str, value: Any) -> "SpanBuilder":
        self._attrs[key] = value
        return self

    def set_start_ts(self, start_ms: int) -> "SpanBuilder":
        self._start_ms = int(start_ms)
        return self

    def finish(self, end_ms: Optional[int] = None) -> Span:
        end = now_ms() if end_ms is None else int(end_ms)
        if end < self._start_ms:        # wall-clock step / caller skew
            end = self._start_ms
        span = Span(self._scope, self._name, self._start_ms, end,
                    dict(self._attrs), self._trace_id, self._span_id,
                    self._parent_id)
        if not self._finished:
            self._finished = True
            self._tracer._report(span)
        return span

    def __enter__(self) -> "SpanBuilder":
        self._start_ms = now_ms()
        self._ctx_cm = use_context(self.context)
        self._ctx_cm.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._ctx_cm is not None:
            self._ctx_cm.__exit__(exc_type, exc, tb)
            self._ctx_cm = None
        self.set_attribute("error", exc_type is not None)
        self.finish()


class TraceReporter:
    """Receives completed spans (reference TraceReporter.addSpan)."""

    def add_span(self, span: Span) -> None:
        raise NotImplementedError


class InMemoryTraceReporter(TraceReporter):
    """Bounded in-memory span ring for tests, REST and the CLI. Retains
    the most recent ``max_retained`` spans (``traces.max-retained``);
    evictions are counted into DEVICE_STATS as ``spans_dropped_total``."""

    def __init__(self, max_retained: int = 4096):
        self.spans: list[Span] = []
        self.max_retained = int(max_retained)
        self.dropped = 0
        self._lock = threading.Lock()

    def add_span(self, span: Span) -> None:
        excess = 0
        with self._lock:
            self.spans.append(span)
            if len(self.spans) > self.max_retained:
                excess = len(self.spans) - self.max_retained
                del self.spans[:excess]
                self.dropped += excess
        if excess:
            _note_spans_dropped(excess)

    def by_name(self, name: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self.spans)

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self.dropped = 0


def _note_spans_dropped(n: int) -> None:
    try:
        from .device import DEVICE_STATS
        DEVICE_STATS.note_spans_dropped(n)
    except Exception:  # noqa: BLE001 - metrics must not kill reporting
        pass


class FlightRecorder(TraceReporter):
    """Always-on, low-overhead post-mortem buffer: a bounded ring of the
    most recent spans and discrete events. ``dump(reason)`` writes the
    ring to a timestamped JSON file (rate-limited per reason) and is
    invoked automatically from the fault chokepoints — watchdog stall,
    region/job restart, corrupt-artifact detection, zombie fence — so
    the seconds *before* a failure are preserved, not just counters."""

    KEEP_DUMPS = 16

    def __init__(self, capacity: int = 512, dump_dir: Optional[str] = None,
                 min_dump_interval_s: float = 1.0):
        self.dump_dir = dump_dir
        self.min_dump_interval_s = min_dump_interval_s
        self.dumps: list[dict] = []
        self._ring: deque = deque(maxlen=int(capacity))
        self._last_dump_ms: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(1, int(capacity)))

    def add_span(self, span: Span) -> None:
        entry = {"type": "span", "ts_ms": span.end_ms}
        entry.update(span.to_dict())
        with self._lock:
            self._ring.append(entry)

    def record_event(self, kind: str, **fields: Any) -> None:
        entry = {"type": "event", "kind": kind, "ts_ms": now_ms()}
        entry.update(fields)
        with self._lock:
            self._ring.append(entry)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str, **fields: Any) -> Optional[str]:
        """Write the current ring to a timestamped file; returns the path,
        or None when rate-limited (same reason within
        ``min_dump_interval_s``) or the write fails."""
        ts = now_ms()
        with self._lock:
            last = self._last_dump_ms.get(reason, 0)
            if ts - last < self.min_dump_interval_s * 1000.0:
                return None
            self._last_dump_ms[reason] = ts
            entries = list(self._ring)
        directory = self.dump_dir or os.path.join(
            tempfile.gettempdir(), "flink_tpu_flight")
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", reason) or "fault"
        path = os.path.join(directory, f"flight-{safe}-{ts}.json")
        payload = {"reason": reason, "dumped_at_ms": ts,
                   "pid": os.getpid(), "entry_count": len(entries),
                   "context": dict(fields), "entries": entries}
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        record = {"reason": reason, "path": path, "ts_ms": ts,
                  "entry_count": len(entries)}
        record.update(fields)
        with self._lock:
            self.dumps.append(record)
            del self.dumps[:-self.KEEP_DUMPS]
        return path

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dumps.clear()
            self._last_dump_ms.clear()


class Tracer:
    """Span factory + reporter fan-out (reference TraceReporterSetup)."""

    def __init__(self, reporters: Optional[list[TraceReporter]] = None):
        self._reporters = list(reporters or [])
        self.enabled = True

    def add_reporter(self, reporter: TraceReporter) -> None:
        self._reporters.append(reporter)

    def span(self, scope: str, name: str,
             parent: Optional[TraceContext] = None) -> SpanBuilder:
        return SpanBuilder(self, scope, name, parent=parent)

    def _report(self, span: Span) -> None:
        if not self.enabled:
            return
        for r in self._reporters:
            try:
                r.add_span(span)
            except Exception:  # noqa: BLE001 - reporters must not kill jobs
                pass

    def retained_spans(self) -> list[Span]:
        """Spans held by the first attached in-memory reporter (the REST
        / CLI inspection surface)."""
        for r in self._reporters:
            if isinstance(r, InMemoryTraceReporter):
                return r.snapshot()
        return []

    def configure(self, config) -> None:
        """Apply ``traces.*`` options (same pattern as FAULTS/WATCHDOG)."""
        from ..core.config import TraceOptions
        self.enabled = bool(config.get(TraceOptions.ENABLED))
        for r in self._reporters:
            if isinstance(r, InMemoryTraceReporter):
                r.max_retained = int(config.get(TraceOptions.MAX_RETAINED))
            elif isinstance(r, FlightRecorder):
                cap = int(config.get(TraceOptions.FLIGHT_CAPACITY))
                if cap != r.capacity:
                    r.set_capacity(cap)
                r.dump_dir = config.get(TraceOptions.FLIGHT_DIR) or None
                r.min_dump_interval_s = float(
                    config.get(TraceOptions.FLIGHT_MIN_INTERVAL))

    def reset(self) -> None:
        """Test hook: clear retained spans and any attached recorder."""
        self.enabled = True
        for r in self._reporters:
            if isinstance(r, InMemoryTraceReporter):
                r.clear()
            elif isinstance(r, FlightRecorder):
                r.reset()


# ---------------------------------------------------------------------------
# Chrome trace-event (Perfetto-loadable) export
# ---------------------------------------------------------------------------

def chrome_trace_events(spans: Iterable[Span], pid: int = 0,
                        counters: Optional[Iterable[dict]] = None) -> dict:
    """Render spans as a Chrome trace-event JSON object (the ``ph: "X"``
    complete-event form) loadable in Perfetto / chrome://tracing. Scopes
    map to tids so each subsystem gets its own track; causal ids ride in
    ``args`` for tree reconstruction.

    ``counters`` takes device-time ledger samples
    (``DEVICE_LEDGER.trace_counters()``: dicts with ``ts_ms``/``site``/
    ``ms``) and renders them as ``ph: "C"`` counter tracks — one
    ``device_ms:<site>`` series per dispatch site, alongside the span
    tracks."""
    tids: Dict[str, int] = {}
    events: List[dict] = []
    for c in counters or ():
        events.append({
            "name": f"device_ms:{c['site']}", "cat": "profiler",
            "ph": "C", "ts": int(c["ts_ms"]) * 1000, "pid": pid,
            "args": {"ms": round(float(c["ms"]), 4)},
        })
    for span in spans:
        tid = tids.setdefault(span.scope, len(tids))
        args: Dict[str, Any] = {
            "trace_id": span.trace_id, "span_id": span.span_id,
        }
        if span.parent_id:
            args["parent_id"] = span.parent_id
        for k, v in span.attributes.items():
            args[k] = v if isinstance(v, (int, float, bool, str)) else str(v)
        events.append({
            "name": span.name, "cat": span.scope, "ph": "X",
            "ts": span.start_ms * 1000,
            "dur": max(span.duration_ms, 0) * 1000,
            "pid": pid, "tid": tid, "args": args,
        })
    for scope, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": scope}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Process-global tracer + flight recorder (singleton pattern of FAULTS /
# WATCHDOG / DEVICE_STATS; configured by every deploy path).
# ---------------------------------------------------------------------------

FLIGHT_RECORDER = FlightRecorder()

TRACER = Tracer()
TRACER.add_reporter(InMemoryTraceReporter())
TRACER.add_reporter(FLIGHT_RECORDER)


def _owning_job(fields: dict) -> dict:
    """Ensure every flight event/dump names its owning job: callers that
    know it pass ``job=...`` explicitly; for the rest the thread-local
    dispatch context (pinned at task-thread start) fills it in, so
    multi-tenant post-mortems can split one ring by failure domain."""
    if not fields.get("job"):
        from .profiler import dispatch_context
        job = dispatch_context()[0]
        if job:
            fields = dict(fields, job=job)
    return fields


def record_flight_event(kind: str, **fields: Any) -> None:
    """Append a discrete (non-span) event to the flight-recorder ring."""
    try:
        FLIGHT_RECORDER.record_event(kind, **_owning_job(fields))
    except Exception:  # noqa: BLE001 - observability must not kill jobs
        pass


def dump_flight_recorder(reason: str, **fields: Any) -> Optional[str]:
    """Record ``reason`` as an event, then dump the ring to a file.
    Called from the fault chokepoints; never raises."""
    try:
        fields = _owning_job(fields)
        FLIGHT_RECORDER.record_event(reason, **fields)
        return FLIGHT_RECORDER.dump(reason, **fields)
    except Exception:  # noqa: BLE001 - observability must not kill jobs
        return None


# Every (scope, name) pair the runtime emits, with its emitting site.
# docs/OBSERVABILITY.md renders this inventory as a table and
# tests/test_tracing.py asserts the two stay identical, so the doc
# cannot rot. Keep entries sorted by (scope, name).
SPAN_INVENTORY: tuple = (
    ("checkpoint", "Align",
     "runtime/stream_task.py — barrier arrival → alignment per subtask"),
    ("checkpoint", "Checkpoint",
     "checkpoint/coordinator.py + cluster/distributed.py — root span, "
     "trigger → complete"),
    ("checkpoint", "Notify",
     "checkpoint/coordinator.py + cluster/distributed.py — completion "
     "fan-out to tasks"),
    ("checkpoint", "Snapshot",
     "runtime/stream_task.py — per-subtask barrier broadcast + state "
     "snapshot + ack"),
    ("checkpoint", "Store",
     "checkpoint/coordinator.py + cluster/distributed.py — artifact "
     "store of the completed checkpoint"),
    ("device", "Compile",
     "metrics/device.py instrumented_program_cache — XLA compile of a "
     "device segment"),
    ("device", "D2H",
     "metrics/device.py note_d2h — device→host transfer"),
    ("device", "Execute",
     "runtime/faults.py DeviceGuard.run — guarded device dispatch "
     "(retries/degrade included)"),
    ("device", "H2D",
     "metrics/device.py note_h2d — host→device transfer"),
    ("ha", "Takeover",
     "cluster/distributed.py CoordinatorContender._on_grant — standby "
     "promoted over a running job: grant → hot resume or fenced restore"),
    ("net", "Fence",
     "cluster/transport.py — zombie producer fenced by epoch check"),
    ("net", "Reconnect",
     "cluster/transport.py — severed data channel redial + replay"),
    ("rescale", "Migrate",
     "runtime/operators/mesh_window.py rescale_live — page ownership "
     "diff + digest-verified key-group transfer"),
    ("rescale", "Rebuild",
     "runtime/operators/mesh_window.py rescale_live — state install on "
     "the new mesh + derived-plane invalidation"),
    ("rescale", "Rescale",
     "cluster/local.py live_rescale + mesh_window rescale_live — root "
     "span, barrier-aligned worker-set change without restart"),
    ("restart", "JobRestart",
     "cluster/scheduler.py + cluster/distributed.py _do_restart — "
     "full-job restart from last verified checkpoint"),
    ("restart", "RegionRestart",
     "cluster/local.py restart_region — failover-region restart"),
    ("restore", "Fallback",
     "checkpoint/coordinator.py — corrupt candidate skipped, older "
     "checkpoint selected"),
    ("restore", "Restore",
     "checkpoint/coordinator.py latest_verified_checkpoint — verified "
     "restore-candidate selection"),
    ("sched", "Admit",
     "runtime/stream_task.py _admission_gate — quota-throttled "
     "micro-batch admission (span covers the gate wait)"),
    ("sched", "Shed",
     "runtime/stream_task.py _admission_gate — overloaded micro-batch "
     "quarantined to the dead-letter output"),
    ("task", "SourceBatch",
     "runtime/stream_task.py — one source read→emit mailbox cycle"),
    ("tier", "Evict",
     "state/tpu_backend.py _evict_cold_groups — cold key groups paged "
     "to the host-warm tier + device table rebuild"),
    ("tier", "Prefetch",
     "state/tiering/prefetch.py PrefetchPipeline — warm key groups "
     "gathered + staged for promotion at a batch boundary"),
    ("watchdog", "Stall",
     "runtime/watchdog.py _note_trip — deadline expiry at a guarded "
     "site"),
)
