"""Metric reporters: push/pull exporters over the registry.

Analog of the reference's reporter stack (flink-metrics: MetricReporter SPI
loaded via ReporterSetup.java:64; flink-metrics-prometheus
PrometheusReporter exposing the registry over HTTP in the Prometheus text
format; flink-metrics-slf4j periodic logging reporter).
"""

from __future__ import annotations

import http.server
import re
import socketserver
import threading
import time
from typing import Any, Callable, Optional

from .core import Counter, Gauge, Histogram, Meter, MetricRegistry

__all__ = ["MetricReporter", "PrometheusReporter", "LoggingReporter",
           "prometheus_text", "register_reporter", "reporters_from_config"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(parts: tuple[str, ...]) -> str:
    return _NAME_RE.sub("_", "_".join(("flink_tpu",) + parts))


def _prom_value(v) -> str:
    """Exposition-format value: finite numbers as-is, non-finite floats
    spelled the way Prometheus expects (NaN/+Inf/-Inf), anything
    non-numeric (a gauge fn returning a string/None/array) as NaN rather
    than corrupting the scrape or raising mid-exposition."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        try:  # numpy scalars and friends quack like floats
            v = float(v)
        except (TypeError, ValueError):
            return "NaN"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
    return repr(v)


def prometheus_text(registry: MetricRegistry) -> str:
    """Render the registry in the Prometheus text exposition format
    (reference PrometheusReporter's collector mapping: Counter->counter,
    Gauge->gauge, Meter->gauge(rate)+counter, Histogram->summary).
    Non-numeric gauge values render NaN; a gauge fn that raises is
    skipped — one bad metric must never take down the whole scrape."""
    lines: list[str] = []
    for scope, m in sorted(registry.all_metrics().items()):
        name = _prom_name(scope)
        if isinstance(m, Counter):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {_prom_value(m.count)}")
        elif isinstance(m, Gauge):
            try:
                v = m.value
            except Exception:  # noqa: BLE001 - gauge fn may race shutdown
                continue
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_prom_value(v)}")
        elif isinstance(m, Meter):
            lines.append(f"# TYPE {name}_rate gauge")
            lines.append(f"{name}_rate {_prom_value(m.rate)}")
            lines.append(f"# TYPE {name}_total counter")
            lines.append(f"{name}_total {_prom_value(m.count)}")
        elif isinstance(m, Histogram):
            # full summary exposition: quantile samples + _sum + _count
            lines.append(f"# TYPE {name} summary")
            for q in (0.5, 0.95, 0.99):
                lines.append(f'{name}{{quantile="{q}"}} '
                             f"{_prom_value(m.quantile(q))}")
            lines.append(f"{name}_sum {_prom_value(m.sum)}")
            lines.append(f"{name}_count {_prom_value(m.count)}")
            # cumulative le-buckets over the same window: quantiles of a
            # summary cannot be aggregated across processes, buckets can
            lines.append(f"# TYPE {name}_bucket histogram")
            for le, c in m.bucket_counts():
                lines.append(f'{name}_bucket{{le="{le}"}} {c}')
    _append_ledger_rollups(lines)
    return "\n".join(lines) + "\n"


def _prom_label(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _append_ledger_rollups(lines: list[str]) -> None:
    """Per-job device-time rollups from the device-time ledger, as
    job-labeled gauges.  Only when the ledger is enabled and has
    attributed anything — a disabled ledger adds zero scrape cost."""
    from .profiler import DEVICE_LEDGER
    if not DEVICE_LEDGER.enabled:
        return
    snap = DEVICE_LEDGER.snapshot()
    if not snap["jobs"]:
        return
    for base, field in (("flink_tpu_profiler_job_device_ms", "device_ms"),
                        ("flink_tpu_profiler_job_compile_ms", "compile_ms"),
                        ("flink_tpu_profiler_job_dispatches", "dispatches")):
        lines.append(f"# TYPE {base} gauge")
        for job, row in sorted(snap["jobs"].items()):
            lines.append(f'{base}{{job="{_prom_label(job)}"}} '
                         f"{_prom_value(row[field])}")


class MetricReporter:
    """Reporter SPI (reference MetricReporter + Scheduled)."""

    def open(self, registry: MetricRegistry) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class PrometheusReporter(MetricReporter):
    """Serves GET /metrics in the text exposition format (pull model)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._requested_port = port
        self._host = host
        self._server = None
        self.port: Optional[int] = None

    def open(self, registry: MetricRegistry) -> None:
        from ..utils.httpd import ThreadedHTTPServer

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = prometheus_text(registry).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence request logging
                pass

        self._server = ThreadedHTTPServer(Handler, self._requested_port,
                                          self._host, "prometheus-reporter")
        self.port = self._server.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None


class LoggingReporter(MetricReporter):
    """Periodic snapshot dump (reference Slf4jReporter); ``sink`` defaults
    to print, injectable for tests."""

    def __init__(self, interval_s: float = 10.0,
                 sink: Optional[Callable[[str], None]] = None):
        self._interval = interval_s
        self._sink = sink or (lambda line: print(line))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def open(self, registry: MetricRegistry) -> None:
        def loop():
            while not self._stop.wait(self._interval):
                snap = registry.snapshot()
                for name in sorted(snap):
                    self._sink(f"{name}={snap[name]}")

        self._thread = threading.Thread(target=loop,
                                        name="logging-reporter", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


# -- name-based reporter loading (reference ReporterSetup.java:64) ----------

_REPORTER_FACTORIES: dict[str, Callable[[], MetricReporter]] = {
    "log": LoggingReporter,
    "prometheus": PrometheusReporter,
}


def register_reporter(name: str,
                      factory: Callable[[], MetricReporter]) -> None:
    """Plugin seam: reporters resolve by name from metrics.reporters."""
    _REPORTER_FACTORIES[name] = factory


def reporters_from_config(config) -> list[MetricReporter]:
    """Instantiate the reporters named in ``metrics.reporters`` (comma-
    separated); unknown names raise with the known set."""
    from ..core.config import MetricOptions

    raw = config.get(MetricOptions.REPORTERS)
    out = []
    for name in (n.strip() for n in str(raw).split(",") if n.strip()):
        factory = _REPORTER_FACTORIES.get(name)
        if factory is None:
            raise ValueError(f"unknown metric reporter {name!r} "
                             f"(known: {sorted(_REPORTER_FACTORIES)})")
        out.append(factory())
    return out
