"""Cluster shared-secret authentication for pickle-bearing endpoints.

The dispatcher's job-submission route, the network log broker, and the
queryable-state server all deserialize pickle received from a socket —
which is code execution in the sender's favor. Matching the reference's
trust model (internal RPC authenticated and fenced; see
SecurityOptions.java and the blob-server secret), every such endpoint:

* resolves a cluster secret from ``security.cluster-secret`` or the
  ``FLINK_TPU_CLUSTER_SECRET`` environment variable;
* REFUSES to bind a non-loopback interface without one (and warns even
  with one — pickle endpoints should also sit behind network controls);
* requires the secret before the first unpickle: socket protocols carry a
  fixed preamble frame per connection, HTTP carries the
  ``X-Flink-Tpu-Token`` header per request. Comparison is constant-time.

Loopback binds with no secret configured skip enforcement — same-host
processes could already debug each other; the boundary being defended is
the network one.
"""

from __future__ import annotations

import hmac
import os
import socket
import struct
import warnings
from typing import Optional

__all__ = [
    "ENV_VAR", "HTTP_HEADER", "resolve_secret", "is_loopback",
    "check_bind", "send_hello", "recv_hello", "token_ok",
]

ENV_VAR = "FLINK_TPU_CLUSTER_SECRET"
HTTP_HEADER = "X-Flink-Tpu-Token"
_MAGIC = b"FTA1"
_HELLO = struct.Struct("<4sH")
_MAX_TOKEN = 1024


def resolve_secret(config=None) -> str:
    """Secret from the configuration, else the environment, else ''."""
    if config is not None:
        from ..core.config import SecurityOptions

        s = config.get(SecurityOptions.CLUSTER_SECRET)
        if s:
            return s
    return os.environ.get(ENV_VAR, "")


def is_loopback(host: str) -> bool:
    # NOTE: "" and "0.0.0.0"/"::" are INADDR_ANY — all interfaces, the
    # OPPOSITE of loopback
    return host in ("localhost", "127.0.0.1", "::1") or \
        host.startswith("127.")


def check_bind(host: str, secret: str, endpoint: str) -> None:
    """Gate a pickle endpoint's bind: non-loopback without a secret is
    refused outright; non-loopback WITH one still warns."""
    if is_loopback(host):
        return
    if not secret:
        raise RuntimeError(
            f"{endpoint} deserializes pickle from the network and would "
            f"bind non-loopback host {host!r} WITHOUT a cluster secret. "
            f"Set {ENV_VAR} (or security.cluster-secret) on every process, "
            "or bind loopback. Refusing to start an unauthenticated pickle "
            "endpoint on a routable interface.")
    warnings.warn(
        f"{endpoint} binding non-loopback host {host!r}: connections are "
        "authenticated with the cluster secret, but pickle endpoints "
        "should additionally sit behind network-level access control",
        RuntimeWarning, stacklevel=3)


def token_ok(token: Optional[str], secret: str) -> bool:
    """Constant-time acceptance check; with no secret configured every
    caller is accepted (loopback-only deployments)."""
    if not secret:
        return True
    return token is not None and hmac.compare_digest(
        token.encode("utf-8"), secret.encode("utf-8"))


def send_hello(sock: socket.socket, secret: str) -> None:
    """Client side of the per-connection preamble (always sent, possibly
    with an empty token, so the framing is version-stable)."""
    tok = secret.encode("utf-8")
    sock.sendall(_HELLO.pack(_MAGIC, len(tok)) + tok)


def recv_hello(sock: socket.socket, secret: str) -> bool:
    """Server side: read the preamble and decide acceptance BEFORE any
    pickle frame is read. False means drop the connection."""
    buf = b""
    while len(buf) < _HELLO.size:
        chunk = sock.recv(_HELLO.size - len(buf))
        if not chunk:
            return False
        buf += chunk
    magic, n = _HELLO.unpack(buf)
    if magic != _MAGIC or n > _MAX_TOKEN:
        return False
    tok = b""
    while len(tok) < n:
        chunk = sock.recv(n - len(tok))
        if not chunk:
            return False
        tok += chunk
    if not secret:
        return True
    return hmac.compare_digest(tok, secret.encode("utf-8"))
