"""Shared threaded-HTTP-server scaffolding for the REST endpoint and the
Prometheus reporter (one server stack to maintain instead of two)."""

from __future__ import annotations

import http.server
import socketserver
import threading
from typing import Optional, Type

__all__ = ["ThreadedHTTPServer"]


class ThreadedHTTPServer:
    """Ephemeral-port threaded HTTP server with daemon lifecycle."""

    def __init__(self, handler: Type[http.server.BaseHTTPRequestHandler],
                 port: int = 0, host: str = "127.0.0.1",
                 name: str = "httpd"):
        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._httpd = _Server((host, port), handler)
        self.port: int = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=name, daemon=True)

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
