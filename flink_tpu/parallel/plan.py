"""Declarative sharding plan: regex partition rules -> PartitionSpec pytrees.

The mesh layer's contract with the rest of the runtime, promoted from an
ad-hoc device list to a first-class object (SNIPPETS [2]/[3] idiom:
``match_partition_rules`` walks a pytree's key paths against ordered regex
rules and yields a `PartitionSpec` pytree; the specs then drive
`shard_map`/`pjit` compilation and `NamedSharding` placement).  Three
invariants live here and are enforced by tpu-lint:

* **Declared axes** (TPU102): every collective in the package names an axis
  from `DECLARED_AXES` — a collective over an undeclared axis either fails
  at trace time on a real mesh or, worse, silently reduces over the wrong
  dimension after a mesh reshape.
* **Local-shape cache keys** (JX505): sharded program builders are keyed by
  `local_signature(...)` — capacity/ring/dtypes only, never the device
  count or a global `[D, ...]` shape — so every device runs the same
  program and adding devices on a rescale never compiles a different key.
* **One mesh axis name per plan**: the data axis is configuration
  (`mesh.axis-rules`), not a per-call argument, so routing, exchange and
  fan-in (`lax.psum`) all agree on the axis they run over.
"""

from __future__ import annotations

import re
import threading
from typing import Any, NamedTuple, Optional, Sequence

import numpy as np

from .mesh import DATA_AXIS, make_mesh, shard_ranges

__all__ = ["AxisRule", "DEFAULT_AXIS_RULES", "DECLARED_AXES",
           "parse_axis_rules", "match_partition_rules", "shard_map_compat",
           "local_shape", "ShardingPlan", "MeshRuntime", "MESH_RUNTIME"]

# Every mesh axis a collective may legally name. The Tier-A lint rule
# TPU102 (analysis/ast_rules.py) resolves collective axis arguments against
# this tuple; extending the mesh to a second axis (e.g. "model") means
# adding it here FIRST, which is exactly the reviewable event the rule
# wants to force.
DECLARED_AXES = (DATA_AXIS,)


class AxisRule(NamedTuple):
    """One ordered partition rule: leaf paths matching ``pattern`` (full
    match against the "/"-joined key path, e.g. ``accs/price``) get
    `PartitionSpec(*axes)`; ``axes == ()`` replicates."""
    pattern: str
    axes: tuple


# Window-state layout: every persistent leaf leads with the device axis
# ([D, ...] over "data"); everything else (scalars, pane bookkeeping)
# replicates. Callers with exotic state pass their own rules or configure
# `mesh.axis-rules`.
DEFAULT_AXIS_RULES = (
    AxisRule(r"(table|dropped|keys|panes|valid)", (DATA_AXIS,)),
    AxisRule(r"(accs|cols|wins|trees|view)(/.*)?", (DATA_AXIS,)),
    AxisRule(r".*", ()),
)


def parse_axis_rules(text: str, axis_name: str = DATA_AXIS
                     ) -> tuple[AxisRule, ...]:
    """``mesh.axis-rules`` syntax: ``;``-separated ``regex=axis`` entries,
    ``regex=*`` (or ``replicated``) meaning replicate; falls back to
    DEFAULT_AXIS_RULES when empty. A catch-all replicate rule is always
    appended so every leaf resolves."""
    text = (text or "").strip()
    if not text:
        return DEFAULT_AXIS_RULES
    rules = []
    for entry in text.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"mesh.axis-rules entry {entry!r} is not 'regex=axis'")
        pattern, axis = (s.strip() for s in entry.rsplit("=", 1))
        re.compile(pattern)  # surface bad regexes at configure time
        if axis in ("*", "replicated", ""):
            rules.append(AxisRule(pattern, ()))
        else:
            if axis not in DECLARED_AXES:
                raise ValueError(
                    f"mesh.axis-rules names undeclared axis {axis!r}; "
                    f"declared: {DECLARED_AXES}")
            rules.append(AxisRule(pattern, (axis,)))
    rules.append(AxisRule(r".*", ()))
    return tuple(rules)


def _path_str(path) -> str:
    parts = []
    for k in path:
        name = getattr(k, "name", None)       # GetAttrKey (namedtuples)
        if name is None:
            name = getattr(k, "key", None)    # DictKey / FlattenedIndexKey
        if name is None:
            name = getattr(k, "idx", None)    # SequenceKey
        parts.append(str(name))
    return "/".join(parts)


def match_partition_rules(rules: Sequence[AxisRule], tree: Any):
    """PartitionSpec pytree for ``tree``: each leaf gets the spec of the
    FIRST rule whose pattern fully matches its "/"-joined key path."""
    import jax
    from jax.sharding import PartitionSpec as P

    def spec_for(path: str):
        for rule in rules:
            if re.fullmatch(rule.pattern, path):
                return P(*rule.axes)
        return P()

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(_path_str(p)) for p, _ in flat])


def shard_map_compat(f, mesh, in_specs, out_specs):
    """`shard_map` across the jax versions this repo targets: newer
    releases expose ``jax.shard_map`` with ``check_vma``; 0.4.x has only
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Both
    checks are disabled — the step emits a psum'd replicated scalar next
    to sharded state, which the static replication checker rejects."""
    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
        except TypeError:  # jax with jax.shard_map but pre-check_vma
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)
    from jax.experimental.shard_map import shard_map as esm
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def local_shape(global_shape: Sequence[int], spec, axis_sizes: dict
                ) -> tuple:
    """Per-device shard shape for a global array under ``spec``: each dim
    named in the spec divides by its mesh-axis size (shard_map semantics:
    exact division is required for sharded dims)."""
    out = list(global_shape)
    for dim, axis in enumerate(tuple(spec)[:len(out)]):
        if axis is None:
            continue
        for ax in ((axis,) if isinstance(axis, str) else axis):
            size = axis_sizes[ax]
            if out[dim] % size:
                raise ValueError(
                    f"dim {dim} of shape {tuple(global_shape)} not "
                    f"divisible by axis {ax!r} (size {size})")
            out[dim] //= size
    return tuple(out)


class ShardingPlan:
    """A mesh + ordered partition rules: the single object the sharded
    window path consults for specs, placement, program mapping, and
    key-group ownership.

    Everything derived from the plan splits into two halves with different
    lifetimes, and keeping them separate is the point of the class:

    * **mesh-dependent** (`sharding`, `device_put`, `shard_map`,
      `ranges`) — changes on rescale;
    * **mesh-independent** (`specs`, `local_signature`) — the program
      cache keys, which must NOT change on rescale so that a worker-set
      change with unchanged local shard shapes recompiles nothing.
    """

    def __init__(self, mesh, rules: Optional[Sequence[AxisRule]] = None,
                 axis_name: str = DATA_AXIS):
        from jax.sharding import NamedSharding, PartitionSpec as P
        self.mesh = mesh
        self.axis_name = axis_name
        self.rules = tuple(rules) if rules else DEFAULT_AXIS_RULES
        self.data_spec = P(axis_name)
        self.state_sharding = NamedSharding(mesh, self.data_spec)

    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def axis_sizes(self) -> dict:
        return dict(self.mesh.shape)

    # -- mesh-independent ------------------------------------------------
    def specs(self, tree):
        """PartitionSpec pytree for ``tree`` under this plan's rules."""
        return match_partition_rules(self.rules, tree)

    def local_signature(self, tree) -> tuple:
        """Canonical local-shard signature: sorted (path, local shape,
        dtype) per leaf, leading ``"local"`` marker. This is the ONLY
        legal program-cache key component derived from arrays (JX505):
        it is invariant under device count, so a rescale that preserves
        per-device shapes hits every cached program."""
        import jax
        from jax.sharding import PartitionSpec as P
        sizes = self.axis_sizes
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        sig = []
        for path, leaf in flat:
            spec = P()
            for rule in self.rules:
                if re.fullmatch(rule.pattern, _path_str(path)):
                    spec = P(*rule.axes)
                    break
            sig.append((_path_str(path),
                        local_shape(np.shape(leaf), spec, sizes),
                        np.dtype(getattr(leaf, "dtype", np.float32)).name))
        return ("local", tuple(sorted(sig)))

    # -- mesh-dependent --------------------------------------------------
    def sharding(self, spec=None):
        from jax.sharding import NamedSharding
        return (self.state_sharding if spec is None
                else NamedSharding(self.mesh, spec))

    def device_put(self, tree):
        """Place a pytree; each leaf lands under its rule's spec."""
        import jax
        specs = self.specs(tree)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, self.sharding(s)), tree, specs)

    def shard_map(self, f, in_specs, out_specs):
        return shard_map_compat(f, self.mesh, in_specs, out_specs)

    def ranges(self, max_parallelism: int, base=None):
        """Contiguous key-group range per mesh position (see
        mesh.shard_ranges for the remainder rules)."""
        return shard_ranges(max_parallelism, self.n_devices, base)


class MeshRuntime:
    """Process-global mesh configuration (singleton, wired by every deploy
    path next to FAULTS/WATCHDOG/TRACER — enforced by TPU201): the parsed
    `mesh.axis-rules`, and the live-rescale policy knobs the coordinator
    consults. configure() is idempotent and cheap."""

    def __init__(self):
        self._lock = threading.Lock()
        self.axis_rules: tuple = DEFAULT_AXIS_RULES
        self.rescale_enabled: bool = True
        self.rescale_timeout_ms: int = 30_000
        self.configured: bool = False

    def configure(self, config) -> None:
        from ..core.config import MeshOptions
        with self._lock:
            self.axis_rules = parse_axis_rules(
                config.get(MeshOptions.AXIS_RULES))
            self.rescale_enabled = bool(
                config.get(MeshOptions.RESCALE_ENABLED))
            self.rescale_timeout_ms = int(
                float(config.get(MeshOptions.RESCALE_TIMEOUT)) * 1000)
            self.configured = True

    def plan(self, mesh, axis_name: str = DATA_AXIS) -> ShardingPlan:
        return ShardingPlan(mesh, rules=self.axis_rules,
                            axis_name=axis_name)

    def reset(self) -> None:
        with self._lock:
            self.axis_rules = DEFAULT_AXIS_RULES
            self.rescale_enabled = True
            self.rescale_timeout_ms = 30_000
            self.configured = False


MESH_RUNTIME = MeshRuntime()
