"""Live key-group rescale: migration planning + the page transfer format.

Flink's canonical state repartitioning (StateAssignmentOperation: key groups
move between operators as whole ranges; SURVEY §5.6) done on the mesh: when
the worker set changes, device-resident window state is re-sharded across
the new mesh WITHOUT a job restart. The transfer representation is the
checkpoint chunk format (checkpoint/storage._page_tpu_snapshot): the keyed
snapshot reordered by (key group, key) and cut into fixed spans of the
max-parallelism key-group space, each page digest-verified (blake2b-128,
the checkpoint chunk digest) before it is applied — a page that fails
verification aborts the rescale instead of installing torn state. Only
pages whose key groups CHANGE owner count as moved; `role="window"` planes
(the derived incremental fire planes) are never shipped — the operator
rebuilds them from the pane accumulators after the switch
(`_mark_inc_dirty`), exactly as after a checkpoint restore.

This module is pure host-side planning over snapshot dicts (the
`_snapshot_backend` format); the operator drives it and owns the device
arrays, the coordinator drives the operator at a barrier.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..core.keygroups import KeyGroupRange

__all__ = ["KeyGroupPage", "MigrationPlan", "paginate_snapshot",
           "plan_migration", "reassemble_pages", "owners_of_groups"]


def owners_of_groups(groups: np.ndarray,
                     ranges: Sequence[KeyGroupRange]) -> np.ndarray:
    """Owning position index per key group under contiguous ``ranges``
    (the inverse of shard_ranges, vectorized; -1 = unowned)."""
    starts = np.array([r.start for r in ranges], np.int64)
    ends = np.array([r.end for r in ranges], np.int64)
    idx = np.searchsorted(starts, np.asarray(groups, np.int64),
                          side="right") - 1
    ok = (idx >= 0) & (np.asarray(groups, np.int64) <= ends[
        np.clip(idx, 0, len(ends) - 1)])
    return np.where(ok, idx, -1).astype(np.int32)


@dataclass(frozen=True)
class KeyGroupPage:
    """One fixed key-group span of a keyed snapshot: the rescale transfer
    unit, laid out exactly like a checkpoint key-group page so the two
    formats stay interchangeable (a rescale could stream pages straight
    out of the last retained checkpoint)."""
    index: int
    group_lo: int               # first key group of the span (inclusive)
    group_hi: int               # last key group of the span (inclusive)
    keys: np.ndarray            # [n] int64, sorted by (group, key)
    key_groups: np.ndarray      # [n] int32
    values: dict                # plane name -> [..., n] (last axis = key)
    digest: str                 # blake2b-128 over keys+groups+values

    @property
    def nbytes(self) -> int:
        return (self.keys.nbytes + self.key_groups.nbytes
                + sum(int(v.nbytes) for v in self.values.values()))


def _page_digest(keys: np.ndarray, groups: np.ndarray,
                 values: dict) -> str:
    h = hashlib.blake2b(digest_size=16)
    h.update(np.ascontiguousarray(keys).tobytes())
    h.update(np.ascontiguousarray(groups).tobytes())
    for name in sorted(values):
        h.update(name.encode())
        h.update(np.ascontiguousarray(values[name]).tobytes())
    return h.hexdigest()


def paginate_snapshot(snap: dict, n_pages: Optional[int] = None
                      ) -> list[KeyGroupPage]:
    """Cut a ``_snapshot_backend``-format dict into key-group pages:
    (key group, key) lexsort + equal spans of the max-parallelism space,
    byte-for-byte the checkpoint page layout (storage._page_tpu_snapshot),
    with the page content digest computed up front."""
    if n_pages is None:
        from ..checkpoint.storage import N_PAGES
        n_pages = N_PAGES
    keys = np.asarray(snap["keys"], np.int64)
    groups = np.asarray(snap["key_groups"], np.int32)
    mp = int(snap.get("max_parallelism") or
             (int(groups.max()) + 1 if len(groups) else 1))
    order = np.lexsort((keys, groups))
    keys, groups = keys[order], groups[order]
    span = (mp + n_pages - 1) // n_pages
    bounds = np.searchsorted(groups, np.arange(1, n_pages) * span)
    key_pages = np.split(keys, bounds)
    group_pages = np.split(groups, bounds)
    value_pages = {
        name: np.split(np.asarray(sd["values"])[..., order], bounds,
                       axis=-1)
        for name, sd in snap.get("states", {}).items()}
    pages = []
    for i in range(n_pages):
        vals = {name: np.ascontiguousarray(parts[i])
                for name, parts in value_pages.items()}
        pages.append(KeyGroupPage(
            index=i, group_lo=i * span,
            group_hi=min((i + 1) * span, mp) - 1,
            keys=key_pages[i], key_groups=group_pages[i], values=vals,
            digest=_page_digest(key_pages[i], group_pages[i], vals)))
    return pages


@dataclass(frozen=True)
class MigrationPlan:
    """What a rescale moves: per-page ownership diff of old vs new shard
    ranges. Pages not in ``moved_pages`` stay resident (every key group
    they hold keeps its owner); the metrics feed
    keygroups_migrated_total / rescale_bytes_moved_total."""
    old_ranges: tuple
    new_ranges: tuple
    pages: tuple                # all KeyGroupPages of the snapshot
    moved_pages: tuple          # indices of pages with >= 1 moved group
    keygroups_migrated: int     # distinct populated groups changing owner
    bytes_moved: int            # row bytes of the moved groups

    @property
    def moved(self) -> tuple:
        return tuple(self.pages[i] for i in self.moved_pages)


def plan_migration(snap: dict, old_ranges: Sequence[KeyGroupRange],
                   new_ranges: Sequence[KeyGroupRange],
                   n_pages: Optional[int] = None) -> MigrationPlan:
    """Diff key-group ownership between two shard layouts over the actual
    snapshot contents. Ownership is compared positionally when the device
    count is unchanged and by range membership otherwise — a group whose
    old owner index has no counterpart in the new layout always moves."""
    pages = paginate_snapshot(snap, n_pages)
    moved_idx, migrated, bytes_moved = [], set(), 0
    for page in pages:
        if len(page.key_groups) == 0:
            continue
        old_own = owners_of_groups(page.key_groups, old_ranges)
        new_own = owners_of_groups(page.key_groups, new_ranges)
        moved = old_own != new_own
        if not moved.any():
            continue
        moved_idx.append(page.index)
        migrated.update(int(g) for g in np.unique(
            page.key_groups[moved]))
        frac = int(moved.sum())
        n = len(page.key_groups)
        # row-exact bytes: keys/groups per moved row + the [..., n] value
        # planes' per-row slice
        bytes_moved += frac * (page.keys.itemsize
                               + page.key_groups.itemsize)
        for v in page.values.values():
            bytes_moved += int(v.nbytes // max(n, 1)) * frac
    return MigrationPlan(
        old_ranges=tuple(old_ranges), new_ranges=tuple(new_ranges),
        pages=tuple(pages), moved_pages=tuple(moved_idx),
        keygroups_migrated=len(migrated), bytes_moved=int(bytes_moved))


def reassemble_pages(pages: Sequence[KeyGroupPage], snap: dict) -> dict:
    """Rebuild a ``_snapshot_backend``-format dict from pages, verifying
    every page digest first (the checkpoint restore contract: corrupt
    transfer bytes abort the rescale before any state is installed)."""
    for page in pages:
        got = _page_digest(page.keys, page.key_groups, page.values)
        if got != page.digest:
            raise RuntimeError(
                f"rescale page {page.index} (key groups "
                f"[{page.group_lo}, {page.group_hi}]) failed digest "
                f"verification: {got} != {page.digest}")
    keys = np.concatenate([p.keys for p in pages]) if pages else \
        np.empty(0, np.int64)
    groups = np.concatenate([p.key_groups for p in pages]) if pages else \
        np.empty(0, np.int32)
    states = {}
    for name, sd in snap.get("states", {}).items():
        vals = (np.concatenate([p.values[name] for p in pages], axis=-1)
                if pages else np.asarray(sd["values"]))
        out = dict(sd)
        out["values"] = vals
        states[name] = out
    return {"kind": snap.get("kind", "tpu"), "keys": keys,
            "key_groups": groups,
            "max_parallelism": snap.get("max_parallelism"),
            "states": states}
