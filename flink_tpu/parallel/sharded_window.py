"""Sharded slice-window aggregation: the multi-chip north-star path.

One compiled step per micro-batch over the WHOLE mesh (SURVEY.md §2.10
data-parallelism row + §5.8): every device holds the keyed state for its
contiguous key-group range (mesh.shard_ranges); a step is

    key-group routing (murmur parity with the host)   ->
    capacity-bounded `all_to_all` keyBy exchange over ICI
    (one round for a uniform batch; skew adds rounds)  ->
    device hash-table lookup-or-insert per shard      ->
    one scatter-fold per aggregate into [ring, cap] pane accumulators

which replaces the reference's per-record WindowOperator.processElement:278 /
KeyGroupStreamPartitioner / Netty channel pipeline. Window fire is one pane
merge over all keys of every shard (SliceSharedWindowAggProcessor semantics);
cross-shard post-aggregations (Nexmark Q5 global hot items) are two-phase:
per-shard top-k then a tiny gather — the
StreamExecLocal/GlobalGroupAggregate split.

Everything here is functional: state is a pytree whose leaves carry a leading
device axis sharded per the ShardingPlan's partition rules, steps compile
through shard_map/pjit, and the host only touches scalars (watermarks, pane
boundaries) — the control plane of the DeviceWindowAggOperator, lifted to N
chips.

Program caching (the rescale-critical invariant, JX505): every builder below
is a module-level `instrumented_program_cache` keyed by
``local_signature(aggs, capacity, ring)`` — the per-device shard shapes and
dtypes, NEVER the device count or a global ``[D, ...]`` shape. All devices
run the same SPMD program, so two meshes with equal local shards share one
cache entry; a live rescale that preserves local shapes recompiles nothing
(the step's key-group ownership bounds are traced arguments, not baked
constants, so even re-pointing a mesh at a different subtask range is free).
The step's shard_map program additionally binds per concrete Mesh inside its
cache entry — changing the axis SIZE lowers new collectives once per size,
while changing device identities or ownership at a fixed size re-dispatches
the already-built program.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..metrics.device import instrumented_program_cache
from ..ops.hash_table import EMPTY_KEY, ensure_x64, lookup_or_insert, \
    make_table
from ..ops.segment_ops import AGG_COMBINE2, AGG_INITS, AGG_INVERT, \
    AGG_MERGES, INVERTIBLE_KINDS, make_accumulator, merge_tree_build, \
    merge_tree_update, pow2_ceil, scatter_fold
from .exchange import bucket_capacity, exchange_round, plan_exchange
from .mesh import DATA_AXIS, device_index_for_key_groups, \
    key_groups_device, shard_ranges
from .plan import ShardingPlan, match_partition_rules, shard_map_compat

__all__ = ["AggDef", "ShardedWindowState", "ShardedWindowAgg",
           "global_topk", "local_signature"]


class AggDef(NamedTuple):
    """One aggregate accumulator: kind in sum|count|min|max.

    ``count`` needs no input column; others fold the column named ``name``
    from the step's value dict. (avg = sum + count at fire, like the
    reference's AggregateFunction.getResult — AggregateFunction.java:114.)
    """
    name: str
    kind: str
    dtype: Any = jnp.float32


class ShardedWindowState(NamedTuple):
    """Pytree of device arrays; leading axis = mesh position ("data")."""
    table: jax.Array            # [D, capacity] int64 key table
    accs: dict                  # name -> [D, ring, capacity]
    dropped: jax.Array          # [D] int64 records lost to table overflow


def _sanitize(keys: jax.Array) -> jax.Array:
    return jnp.where(keys == jnp.int64(EMPTY_KEY),
                     jnp.int64(EMPTY_KEY) - 1, keys.astype(jnp.int64))


# ----------------------------------------------------------------------
# local-shard program-cache keys
# ----------------------------------------------------------------------

def local_signature(aggs: Sequence[AggDef], capacity: int, ring: int
                    ) -> tuple:
    """The canonical program-cache key: aggregate schema + per-device
    shard dims. Fully determines every local leaf — table [1, capacity]
    int64, accs [1, ring, capacity] per dtype, dropped [1] int64 — and is
    invariant under device count and mesh identity, which is what lets a
    rescale hit every cached program (JX505 pins this contract)."""
    return ("local",
            tuple((a.name, a.kind, np.dtype(a.dtype).name) for a in aggs),
            int(capacity), int(ring))


def _aggs_from_sig(agg_sig) -> list[AggDef]:
    return [AggDef(name, kind, np.dtype(dt)) for name, kind, dt in agg_sig]


def _split_sig(agg_sig):
    inv = tuple((kind, name) for name, kind, _ in agg_sig
                if kind in INVERTIBLE_KINDS)
    tree = tuple((kind, name) for name, kind, _ in agg_sig
                 if kind not in INVERTIBLE_KINDS)
    return inv, tree


# ----------------------------------------------------------------------
# module-level program builders (shared across instances and meshes)
# ----------------------------------------------------------------------

@instrumented_program_cache("mesh.step")
def _step_program(sig, max_parallelism: int, axis_name: str,
                  rules: tuple):
    """The sharded fold step. The returned dispatcher takes the concrete
    Mesh as its first argument and binds the shard_map program per mesh
    inside this one cache entry: the cache key stays local-shape-only
    while the executable still closes over the mesh jax 0.4.x requires."""
    _, agg_sig, cap, ring = sig
    aggs = _aggs_from_sig(agg_sig)
    MP = max_parallelism

    def bind(mesh: Mesh):
        # lint: sync-ok mesh.devices is a host numpy array of Device objects
        D = int(mesh.devices.size)

        def shard_body(table, accs, dropped, keys, cols, panes, valid,
                       base_start, base_len):
            table, keys = table[0], keys[0]
            accs = {k: v[0] for k, v in accs.items()}
            cols = {k: v[0] for k, v in cols.items()}
            panes, valid = panes[0], valid[0]

            kg = key_groups_device(keys, MP)
            # ownership bounds are TRACED scalars: a rescale that re-points
            # this mesh at a different subtask range changes only argument
            # values, never the program
            dest = device_index_for_key_groups(kg, D, MP, base_start,
                                               base_len)
            # rows outside this subtask's range never fold (they belong to
            # a peer host; a correct upstream exchange never sends them)
            valid = valid & (dest >= 0) & (dest < D)
            payload = {"__key__": _sanitize(keys), "__pane__": panes, **cols}

            # capacity-bounded exchange: rounds of `cap_x` rows per
            # destination keep the per-device fold width O(B) as the mesh
            # grows (the worst-case-width keyby_exchange folds D*B rows
            # per device — anti-scaling). The trip count is pmax-uniform
            # across the axis so the collectives inside the loop line up;
            # a skewed batch takes more rounds but never loses a record.
            B = keys.shape[0]
            cap_x = bucket_capacity(B, D)
            xplan = plan_exchange(dest, valid, D, cap_x)
            ordered = jax.tree.map(lambda c: c[xplan.order], payload)
            n_rounds = jax.lax.pmax(xplan.n_rounds, axis_name)

            def fold_round(carry):
                r, table, accs, dropped, ok_count = carry
                accs = dict(accs)
                routed, rvalid = exchange_round(axis_name, D, cap_x, xplan,
                                                ordered, r)
                table, slots, ok = lookup_or_insert(
                    table, routed["__key__"], rvalid)
                n_dropped = jnp.sum(rvalid & ~ok).astype(jnp.int64)
                ring_idx = jnp.where(ok, (routed["__pane__"] % ring),
                                     0).astype(jnp.int32)
                flat = ring_idx * cap + jnp.maximum(slots, 0)
                for a in aggs:
                    vals = (jnp.ones(flat.shape[0], a.dtype)
                            if a.kind == "count" else routed[a.name])
                    accs[a.name] = scatter_fold(
                        a.kind, accs[a.name].reshape(-1), flat, vals,
                        ok).reshape(ring, cap)
                return (r + 1, table, accs, dropped + n_dropped,
                        ok_count + jnp.sum(ok).astype(jnp.int64))

            carry = (jnp.int32(0), table, accs, dropped,
                     jnp.zeros((), jnp.int64))
            _, table, accs, dropped, ok_count = jax.lax.while_loop(
                lambda c: c[0] < n_rounds, fold_round, carry)
            processed = jax.lax.psum(ok_count, axis_name)
            return (table[None], {k: v[None] for k, v in accs.items()},
                    dropped, processed)

        skel = {"table": 0, "accs": {a.name: 0 for a in aggs},
                "dropped": 0, "keys": 0,
                "cols": {a.name: 0 for a in aggs if a.kind != "count"},
                "panes": 0, "valid": 0}
        sp = match_partition_rules(rules, skel)
        state_specs = (sp["table"], sp["accs"], sp["dropped"])
        mapped = shard_map_compat(
            shard_body, mesh,
            in_specs=state_specs + (sp["keys"], sp["cols"], sp["panes"],
                                    sp["valid"], P(), P()),
            out_specs=state_specs + (P(),))

        @jax.jit
        def step(state: ShardedWindowState, keys, cols, panes, valid,
                 base_start, base_len):
            table, accs, dropped, processed = mapped(
                state.table, state.accs, state.dropped, keys, cols, panes,
                valid, base_start, base_len)
            return ShardedWindowState(table, accs, dropped), processed

        return step

    bound: dict = {}

    def dispatch(mesh: Mesh, state, keys, cols, panes, valid,
                 base_start, base_len):
        prog = bound.get(mesh)
        if prog is None:
            prog = bound[mesh] = bind(mesh)
        return prog(state, keys, cols, panes, valid, base_start, base_len)

    return dispatch


@instrumented_program_cache("mesh.fire")
def _fire_program(sig):
    _, agg_sig, _cap, _ring = sig
    aggs = _aggs_from_sig(agg_sig)
    count_name = next(name for name, kind, _ in agg_sig if kind == "count")

    @jax.jit
    def fire(state: ShardedWindowState, pane_rows: jax.Array,
             rows_valid: jax.Array):
        def merge(kind, arr):
            sub = arr[:, pane_rows, :]              # [D, W, cap]
            ident = AGG_INITS[kind](arr.dtype)
            sub = jnp.where(rows_valid[None, :, None], sub, ident)
            return AGG_MERGES[kind](sub, axis=1)

        out = {a.name: merge(a.kind, state.accs[a.name]) for a in aggs}
        count = out[count_name]
        emit = (state.table != jnp.int64(EMPTY_KEY)) & (count > 0)
        return out, emit

    return fire


@instrumented_program_cache("mesh.fire_full")
def _fire_full_program(sig, rank_name: Optional[str], topk: Optional[int]):
    """ONE compiled program for the whole fire (the mesh twin of
    device_window._fire_program): pane merge for every aggregate + emit
    mask + optional two-phase global top-k (per-shard lax.top_k, merge of
    D*k candidates) + health scalars (max shard occupancy, total drops)
    riding in the same outputs, so the hot loop never pays a separate sync
    for pressure checks. Everything it returns is materialized with ONE
    async device->host copy — never the full [D, capacity] table when a
    top-k is requested."""
    _, agg_sig, _cap, _ring = sig
    aggs = _aggs_from_sig(agg_sig)
    count_name = next(name for name, kind, _ in agg_sig if kind == "count")

    @jax.jit
    def fire(state: ShardedWindowState, pane_rows, rows_valid):
        def merge(kind, arr):
            sub = arr[:, pane_rows, :]              # [D, W, cap]
            ident = AGG_INITS[kind](arr.dtype)
            sub = jnp.where(rows_valid[None, :, None], sub, ident)
            return AGG_MERGES[kind](sub, axis=1)

        out = {a.name: merge(a.kind, state.accs[a.name]) for a in aggs}
        count = out[count_name]
        emit = (state.table != jnp.int64(EMPTY_KEY)) & (count > 0)
        occ = (state.table != jnp.int64(EMPTY_KEY)).sum(axis=1).max()
        dropped = state.dropped.sum()
        if topk is None:
            return state.table, emit, out, dropped, occ
        rank = out[rank_name]
        _vals, flat_idx, ok = global_topk(rank, emit, topk)
        keys = jnp.take(state.table.reshape(-1), flat_idx)
        res = {n: jnp.take(v.reshape(-1), flat_idx)
               for n, v in out.items()}
        return keys, ok, res, dropped, occ

    return fire


@instrumented_program_cache("mesh.seal_inc")
def _seal_inc_program(sig):
    """ONE donated program per pane seal: for each invertible plane,
    window' = (window ⊕ sealed pane) ⊖ retiring pane; for each merge
    tree, clear the retiring leaf then write the sealed pane and
    recompute both O(log L) ancestor paths. Returns the fire view
    ([D, capacity] per plane) alongside the new planes — the fire
    consumes the view without re-reading any ring row."""
    _, agg_sig, _cap, _ring = sig
    inv_sig, tree_sig = _split_sig(agg_sig)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def seal(state: ShardedWindowState, wins: dict, trees: dict,
             new_row, sub_row, sub_valid, new_leaf, old_leaf):
        view, new_wins, new_trees = {}, {}, {}
        for kind, name in inv_sig:
            arr = state.accs[name]                  # [D, ring, cap]
            sealed = jnp.take(arr, new_row, axis=1)  # [D, cap]
            fire_v = AGG_COMBINE2[kind](wins[name], sealed)
            ident = AGG_INITS[kind](arr.dtype)
            retire = jnp.where(sub_valid,
                               jnp.take(arr, sub_row, axis=1), ident)
            new_wins[name] = AGG_INVERT[kind](fire_v, retire)
            view[name] = fire_v
        for kind, name in tree_sig:
            arr = state.accs[name]
            ident = jnp.full((arr.shape[0], arr.shape[2]),
                             AGG_INITS[kind](arr.dtype), arr.dtype)
            # clear the retiring leaf FIRST: it can never be the pane
            # being sealed (any two live panes differ by < L)
            tree = jax.vmap(
                lambda t, v: merge_tree_update(kind, t, old_leaf, v)
            )(trees[name], ident)
            tree = jax.vmap(
                lambda t, v: merge_tree_update(kind, t, new_leaf, v)
            )(tree, jnp.take(arr, new_row, axis=1))
            new_trees[name] = tree
            view[name] = tree[:, 1]
        return view, new_wins, new_trees

    return seal


@instrumented_program_cache("mesh.rebuild_inc")
def _rebuild_inc_program(sig):
    """Re-derive the incremental planes from the pane accumulators in
    one dispatch (restore, degrade, fire-boundary jump, or a write
    into an already-sealed pane). ``pane_rows``/``pane_leaves`` are
    padded to [ring] so the program shape is window-width-independent;
    padding rows carry leaf index L and drop out of the scatter."""
    _, agg_sig, _cap, ring = sig
    inv_sig, tree_sig = _split_sig(agg_sig)
    L = pow2_ceil(ring)

    @jax.jit
    def rebuild(state: ShardedWindowState, pane_rows, rows_valid,
                pane_leaves, sub_row, sub_valid):
        view, new_wins, new_trees = {}, {}, {}
        for kind, name in inv_sig:
            arr = state.accs[name]
            ident = AGG_INITS[kind](arr.dtype)
            sub = jnp.where(rows_valid[None, :, None],
                            arr[:, pane_rows, :], ident)
            fire_v = AGG_MERGES[kind](sub, axis=1)   # [D, cap]
            retire = jnp.where(sub_valid,
                               jnp.take(arr, sub_row, axis=1), ident)
            new_wins[name] = AGG_INVERT[kind](fire_v, retire)
            view[name] = fire_v
        for kind, name in tree_sig:
            arr = state.accs[name]
            ident = AGG_INITS[kind](arr.dtype)
            rows = jnp.where(rows_valid[None, :, None],
                             arr[:, pane_rows, :], ident)
            leaves = jnp.full((arr.shape[0], L, arr.shape[2]), ident,
                              arr.dtype)
            idx = jnp.where(rows_valid, pane_leaves, L)
            leaves = leaves.at[:, idx, :].set(rows, mode="drop")
            tree = jax.vmap(lambda lv: merge_tree_build(kind, lv))(
                leaves)
            new_trees[name] = tree
            view[name] = tree[:, 1]
        return view, new_wins, new_trees

    return rebuild


@instrumented_program_cache("mesh.fire_inc")
def _fire_inc_program(sig, rank_name: Optional[str], topk: Optional[int]):
    """The fused fire over an incremental view: emit mask + optional
    global top-k + health scalars — identical output structure to
    _fire_full_program, but reading [D, capacity] views instead of
    merging W ring rows."""
    _, agg_sig, _cap, _ring = sig
    count_name = next(name for name, kind, _ in agg_sig if kind == "count")

    @jax.jit
    def fire(state: ShardedWindowState, view: dict):
        count = view[count_name]
        emit = (state.table != jnp.int64(EMPTY_KEY)) & (count > 0)
        occ = (state.table != jnp.int64(EMPTY_KEY)).sum(axis=1).max()
        dropped = state.dropped.sum()
        if topk is None:
            return state.table, emit, view, dropped, occ
        rank = view[rank_name]
        _vals, flat_idx, ok = global_topk(rank, emit, topk)
        keys = jnp.take(state.table.reshape(-1), flat_idx)
        res = {n: jnp.take(v.reshape(-1), flat_idx)
               for n, v in view.items()}
        return keys, ok, res, dropped, occ

    return fire


@instrumented_program_cache("mesh.retire")
def _retire_program(sig):
    _, agg_sig, _cap, _ring = sig
    aggs = _aggs_from_sig(agg_sig)

    @jax.jit
    def retire(state: ShardedWindowState, row: jax.Array):
        accs = {
            a.name: state.accs[a.name].at[:, row].set(
                AGG_INITS[a.kind](state.accs[a.name].dtype))
            for a in aggs}
        return state._replace(accs=accs)

    return retire


class ShardedWindowAgg:
    """Facade over the cached sharded programs for one (mesh, schema).

    Static schema (aggregates, capacity, ring) forms the local-shard
    signature the module-level program caches key on; the mesh and the
    key-group ownership are PER-INSTANCE runtime state — rebuilding an
    instance on a new mesh (grow, restore, live rescale) with the same
    signature reuses every already-compiled program.
    """

    def __init__(self, mesh: Mesh, aggs: Sequence[AggDef],
                 capacity: int = 1 << 16, ring: int = 64,
                 max_parallelism: int = 128, base_range=None,
                 plan: Optional[ShardingPlan] = None):
        """``base_range``: restrict this mesh to one SUBTASK's key-group
        range (multi-host deployment: the vertex is parallelized across
        hosts over DCN, each host's mesh owns its subtask range and
        re-shards it across local devices over ICI). None = full space
        (single-host mesh vertex). ``plan``: partition rules + axis; by
        default the configured MESH_RUNTIME rules over ``mesh``."""
        ensure_x64()
        if capacity & (capacity - 1):
            raise ValueError("capacity must be a power of two")
        if plan is None:
            from .plan import MESH_RUNTIME
            plan = MESH_RUNTIME.plan(mesh)
        self.plan = plan
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        if max_parallelism < self.n_dev:
            raise ValueError("max_parallelism must be >= mesh size")
        self.aggs = list(aggs)
        if not any(a.kind == "count" for a in self.aggs):
            self.aggs.append(AggDef("__count__", "count", jnp.int64))
        names = [a.name for a in self.aggs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate aggregate names: {names}")
        self.capacity = capacity
        self.ring = ring
        self.max_parallelism = max_parallelism
        self._sharding = plan.state_sharding
        # incremental fire engine plane split (window.fire.incremental):
        # invertible aggregates keep a running [D, capacity] window
        # accumulator; min/max keep a [D, 2L, capacity] binary merge tree
        # over ring pane rows. L tracks the RING (not the window width) so
        # the compiled seal/rebuild shapes are independent of W.
        self.tree_size = pow2_ceil(ring)
        self.inv_sig = tuple((a.kind, a.name) for a in self.aggs
                             if a.kind in INVERTIBLE_KINDS)
        self.tree_sig = tuple((a.kind, a.name) for a in self.aggs
                              if a.kind not in INVERTIBLE_KINDS)
        self._step = _step_program(self.sig, max_parallelism,
                                   plan.axis_name, plan.rules)
        self._fire = _fire_program(self.sig)
        self._retire = _retire_program(self.sig)
        self.set_base_range(base_range)

    # ------------------------------------------------------------------
    def set_base_range(self, base_range) -> None:
        """Re-point this mesh at a (new) subtask key-group range WITHOUT
        recompiling: ownership bounds are traced step arguments, so a live
        ownership change (key-group redistribution across an unchanged
        worker set) only changes argument values."""
        self.base_range = base_range
        self.shard_ranges = shard_ranges(self.max_parallelism, self.n_dev,
                                         base_range)
        start = self.shard_ranges[0].start
        self._base_start = np.int32(start)
        self._base_len = np.int32(self.shard_ranges[-1].end - start + 1)

    # ------------------------------------------------------------------
    def init_state(self) -> ShardedWindowState:
        D, cap, ring = self.n_dev, self.capacity, self.ring
        state = ShardedWindowState(
            jnp.tile(make_table(cap)[None], (D, 1)),
            {a.name: jnp.tile(
                make_accumulator(a.kind, (ring, cap), a.dtype)[None],
                (D, 1, 1)) for a in self.aggs},
            jnp.zeros(D, jnp.int64))
        with self.mesh:
            return self.plan.device_put(state)

    # ------------------------------------------------------------------
    @property
    def sig(self):
        """Local-shape program-cache key (JX505): per-device shard shapes
        only — derived, so partially-constructed test doubles get it too."""
        return local_signature(self.aggs, self.capacity, self.ring)

    # ------------------------------------------------------------------
    def step(self, state: ShardedWindowState, keys: jax.Array, cols: dict,
             panes: jax.Array, valid: jax.Array
             ) -> tuple[ShardedWindowState, jax.Array]:
        """Fold one micro-batch. keys/panes/valid: [D, B]; cols: dict of
        [D, B] value columns (one per non-count aggregate)."""
        return self._step(self.mesh, state, keys, cols, panes, valid,
                          self._base_start, self._base_len)

    # ------------------------------------------------------------------
    def fire(self, state: ShardedWindowState, pane_rows: np.ndarray,
             rows_valid: Optional[np.ndarray] = None
             ) -> tuple[dict, jax.Array]:
        """Merge the given ring rows into per-key window results
        ([D, capacity] per aggregate) + emit mask. Keys = state.table.
        Callers firing at a fixed cadence should pad ``pane_rows`` to a
        constant width and mask with ``rows_valid`` so the program
        compiles once."""
        if rows_valid is None:
            rows_valid = np.ones(len(pane_rows), bool)
        return self._fire(state, jnp.asarray(pane_rows, jnp.int32),
                          jnp.asarray(rows_valid))

    # ------------------------------------------------------------------
    def _fire_full_program(self, rank_name: Optional[str],
                           topk: Optional[int]):
        return _fire_full_program(self.sig, rank_name, topk)

    def fire_compact(self, state: ShardedWindowState, pane_rows: np.ndarray,
                     rows_valid: np.ndarray, rank_name: Optional[str],
                     topk: Optional[int]):
        """Dispatch the fused fire; returns device outputs (see
        _fire_full_program) without synchronizing."""
        return self._fire_full_program(rank_name, topk)(
            state, jnp.asarray(pane_rows, jnp.int32),
            jnp.asarray(rows_valid))

    # -- incremental fire engine ---------------------------------------
    def seal_inc(self, state: ShardedWindowState, wins: dict, trees: dict,
                 new_row: int, sub_row: int, sub_valid: bool,
                 new_leaf: int, old_leaf: int):
        """Seal one pane into the incremental planes (wins/trees are
        donated) and return (fire view, new wins, new trees)."""
        return _seal_inc_program(self.sig)(
            state, wins, trees, jnp.int32(new_row), jnp.int32(sub_row),
            jnp.bool_(sub_valid), jnp.int32(new_leaf), jnp.int32(old_leaf))

    def rebuild_inc(self, state: ShardedWindowState, pane_rows: np.ndarray,
                    rows_valid: np.ndarray, pane_leaves: np.ndarray,
                    sub_row: int, sub_valid: bool):
        """Rebuild the incremental planes from the pane accumulators;
        same return shape as seal_inc."""
        return _rebuild_inc_program(self.sig)(
            state, jnp.asarray(pane_rows, jnp.int32),
            jnp.asarray(rows_valid), jnp.asarray(pane_leaves, jnp.int32),
            jnp.int32(sub_row), jnp.bool_(sub_valid))

    def fire_inc(self, state: ShardedWindowState, view: dict,
                 rank_name: Optional[str], topk: Optional[int]):
        """Dispatch the fused incremental fire; returns device outputs
        (same structure as fire_compact) without synchronizing."""
        return _fire_inc_program(self.sig, rank_name, topk)(state, view)

    # ------------------------------------------------------------------
    def retire_row(self, state: ShardedWindowState,
                   row: int) -> ShardedWindowState:
        """Reset one ring row across all shards (pane retirement)."""
        return self._retire(state, jnp.int32(row))


@functools.partial(jax.jit, static_argnames=("k",))
def global_topk(values: jax.Array, valid: jax.Array, k: int
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Two-phase global top-k over sharded [D, capacity] per-key values
    (Nexmark Q5 hot items): per-shard top-k, then merge the D*k candidates.
    Returns (values [k], flat indices [k] into the [D*capacity] layout,
    ok [k] bool). Entries with ok=False are padding (fewer than k valid
    slots existed); their values/indices must be ignored — for integer
    dtypes the sentinel is indistinguishable from a real minimum, so
    always filter on ``ok``, not on the values."""
    neg = (jnp.finfo(values.dtype).min
           if jnp.issubdtype(values.dtype, jnp.floating)
           else jnp.iinfo(values.dtype).min)
    masked = jnp.where(valid, values, neg)
    D, cap = masked.shape
    kk = min(k, cap)
    local_v, local_i = jax.lax.top_k(masked, kk)          # [D, kk]
    local_ok = jnp.take_along_axis(valid, local_i, axis=1)
    flat_i = local_i + (jnp.arange(D, dtype=jnp.int32)[:, None] * cap)
    merged_v, sel = jax.lax.top_k(local_v.reshape(-1), min(k, D * kk))
    return merged_v, flat_i.reshape(-1)[sel], local_ok.reshape(-1)[sel]
