"""keyBy exchange over ICI: the all-to-all repartition.

This replaces the reference's hash repartition between subtasks
(KeyGroupStreamPartitioner + RecordWriter.emit:104 + the Netty
credit-based channel stack, SURVEY.md §5.8) with ONE XLA collective: every
device buckets its local micro-batch by destination shard and a single
`lax.all_to_all` rides the ICI mesh. There are no credits — collectives are
synchronous, so backpressure collapses to admission control at ingestion
(SURVEY.md §7 hard-parts).

Two shapes of the same exchange live here:

* ``keyby_exchange`` — the worst-case-width form: each device sends a
  [n_dev, B] buffer (capacity B per destination — the whole local batch
  may hash to one shard), so ONE collective always suffices but every
  receiver folds n_dev*B rows. Per-device cost grows linearly with the
  mesh, which is exactly the anti-scaling the multichip bench exposed.
* ``plan_exchange`` + ``exchange_round`` — the capacity-bounded form the
  sharded window step uses: buckets are cut into rounds of ``cap`` rows
  per destination and the step loops rounds until the DEEPEST bucket
  across the mesh is drained (`lax.pmax` of the local round counts, so
  every device runs the same trip count and the collectives stay
  uniform). A uniform batch takes one round of ~B/n_dev-deep buckets —
  per-device fold width stays O(B) as the mesh grows; a fully skewed
  batch degrades to ceil(B/cap) rounds, the old worst case, but never
  drops a record.

Invalid (padding) rows are routed to a virtual overflow destination and
vanish in both forms.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["keyby_exchange", "plan_exchange", "exchange_round",
           "ExchangePlan"]


def keyby_exchange(axis_name: str, n_dev: int, dest: jax.Array,
                   payload: Any, valid: jax.Array) -> tuple[Any, jax.Array]:
    """Route records to their destination shard. Call INSIDE shard_map.

    dest:    [B] int32 destination mesh position per record
    payload: pytree of [B, ...] column arrays
    valid:   [B] bool — padding rows are discarded

    Returns (routed payload pytree of [n_dev * B, ...], routed valid mask
    [n_dev * B]); routed rows are grouped by source device.
    """
    B = dest.shape[0]
    d = jnp.where(valid, dest, jnp.int32(n_dev))  # invalid -> overflow bucket
    order = jnp.argsort(d, stable=True)
    sd = d[order]
    counts = jnp.sum(jax.nn.one_hot(d, n_dev + 1, dtype=jnp.int32), axis=0)
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(B, dtype=jnp.int32) - offsets[sd]

    send_valid = jnp.zeros((n_dev, B), bool).at[sd, rank].set(
        sd < n_dev, mode="drop")

    def scatter(col):
        buf = jnp.zeros((n_dev, B) + col.shape[1:], col.dtype)
        return buf.at[sd, rank].set(col[order], mode="drop")

    send = jax.tree.map(scatter, payload)
    if n_dev == 1:
        recv, recv_valid = send, send_valid
    else:
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(x, axis_name, split_axis=0,
                                         concat_axis=0), send)
        recv_valid = jax.lax.all_to_all(send_valid, axis_name, split_axis=0,
                                        concat_axis=0)
    routed = jax.tree.map(
        lambda x: x.reshape((n_dev * B,) + x.shape[2:]), recv)
    return routed, recv_valid.reshape(n_dev * B)


class ExchangePlan(NamedTuple):
    """Routing plan for the capacity-bounded exchange (see module doc).

    order:    [B] int32 — stable sort permutation grouping rows by dest
    sd:       [B] int32 — destination of each ordered row (n_dev = padding)
    rank:     [B] int32 — position of each ordered row within its bucket
    n_rounds: []  int32 — LOCAL round count; `lax.pmax` it across the
              axis before looping so every device runs the same trips
    """
    order: jax.Array
    sd: jax.Array
    rank: jax.Array
    n_rounds: jax.Array


def bucket_capacity(batch: int, n_dev: int) -> int:
    """Static per-destination round capacity for a local batch of `batch`.

    Mean bucket depth is batch/n_dev; the +25% (floor +16) headroom keeps
    a uniformly keyed batch to one round with high probability while a
    skewed batch just takes more rounds — capacity never loses records.
    """
    per = -(-batch // n_dev)
    return int(min(batch, max(32, per + max(per // 4, 16))))


def plan_exchange(dest: jax.Array, valid: jax.Array, n_dev: int,
                  cap: int) -> ExchangePlan:
    """Bucket a local batch by destination for round-based exchange.

    Call INSIDE shard_map. `cap` must be a static int (shapes depend on
    it); `bucket_capacity` picks a good default.
    """
    B = dest.shape[0]
    d = jnp.where(valid, dest, jnp.int32(n_dev))
    order = jnp.argsort(d, stable=True)
    sd = d[order]
    counts = jnp.sum(jax.nn.one_hot(d, n_dev + 1, dtype=jnp.int32), axis=0)
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(B, dtype=jnp.int32) - offsets[sd]
    deepest = jnp.max(counts[:n_dev])
    n_rounds = (deepest + jnp.int32(cap - 1)) // jnp.int32(cap)
    return ExchangePlan(order, sd, rank, n_rounds)


def exchange_round(axis_name: str, n_dev: int, cap: int, plan: ExchangePlan,
                   ordered_payload: Any, r: jax.Array) -> tuple[Any, jax.Array]:
    """Route round `r` of a planned exchange: rows with bucket rank in
    [r*cap, (r+1)*cap). `ordered_payload` columns must already be permuted
    by `plan.order`. Returns ([n_dev*cap, ...] routed pytree, [n_dev*cap]
    valid mask). Safe inside lax.while_loop with a pmax-uniform trip count.
    """
    sub = plan.rank - r * jnp.int32(cap)
    in_round = (sub >= 0) & (sub < cap) & (plan.sd < n_dev)
    # Out-of-round rows get an out-of-bounds slot so mode="drop" discards
    # them (negative indices would wrap under the default mode).
    slot = jnp.where(in_round, sub, jnp.int32(cap))

    send_valid = jnp.zeros((n_dev, cap), bool).at[plan.sd, slot].set(
        in_round, mode="drop")

    def scatter(col):
        buf = jnp.zeros((n_dev, cap) + col.shape[1:], col.dtype)
        return buf.at[plan.sd, slot].set(col, mode="drop")

    send = jax.tree.map(scatter, ordered_payload)
    if n_dev == 1:
        recv, recv_valid = send, send_valid
    else:
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(x, axis_name, split_axis=0,
                                         concat_axis=0), send)
        recv_valid = jax.lax.all_to_all(send_valid, axis_name, split_axis=0,
                                        concat_axis=0)
    routed = jax.tree.map(
        lambda x: x.reshape((n_dev * cap,) + x.shape[2:]), recv)
    return routed, recv_valid.reshape(n_dev * cap)
