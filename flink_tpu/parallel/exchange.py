"""keyBy exchange over ICI: the all-to-all repartition.

This replaces the reference's hash repartition between subtasks
(KeyGroupStreamPartitioner + RecordWriter.emit:104 + the Netty
credit-based channel stack, SURVEY.md §5.8) with ONE XLA collective: every
device buckets its local micro-batch by destination shard and a single
`lax.all_to_all` rides the ICI mesh. There are no credits — collectives are
synchronous, so backpressure collapses to admission control at ingestion
(SURVEY.md §7 hard-parts).

Shapes are static: each device sends a [n_dev, B] buffer (capacity B per
destination — worst case the whole local batch hashes to one shard), so no
record is ever dropped by the exchange itself; invalid (padding) rows are
routed to a virtual overflow destination and vanish.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["keyby_exchange"]


def keyby_exchange(axis_name: str, n_dev: int, dest: jax.Array,
                   payload: Any, valid: jax.Array) -> tuple[Any, jax.Array]:
    """Route records to their destination shard. Call INSIDE shard_map.

    dest:    [B] int32 destination mesh position per record
    payload: pytree of [B, ...] column arrays
    valid:   [B] bool — padding rows are discarded

    Returns (routed payload pytree of [n_dev * B, ...], routed valid mask
    [n_dev * B]); routed rows are grouped by source device.
    """
    B = dest.shape[0]
    d = jnp.where(valid, dest, jnp.int32(n_dev))  # invalid -> overflow bucket
    order = jnp.argsort(d, stable=True)
    sd = d[order]
    counts = jnp.sum(jax.nn.one_hot(d, n_dev + 1, dtype=jnp.int32), axis=0)
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(B, dtype=jnp.int32) - offsets[sd]

    send_valid = jnp.zeros((n_dev, B), bool).at[sd, rank].set(
        sd < n_dev, mode="drop")

    def scatter(col):
        buf = jnp.zeros((n_dev, B) + col.shape[1:], col.dtype)
        return buf.at[sd, rank].set(col[order], mode="drop")

    send = jax.tree.map(scatter, payload)
    if n_dev == 1:
        recv, recv_valid = send, send_valid
    else:
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(x, axis_name, split_axis=0,
                                         concat_axis=0), send)
        recv_valid = jax.lax.all_to_all(send_valid, axis_name, split_axis=0,
                                        concat_axis=0)
    routed = jax.tree.map(
        lambda x: x.reshape((n_dev * B,) + x.shape[2:]), recv)
    return routed, recv_valid.reshape(n_dev * B)
