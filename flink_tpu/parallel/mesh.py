"""Mesh helpers + device-side key-group routing.

The multi-chip analog of the reference's key-group assignment
(KeyGroupRangeAssignment.java: assignToKeyGroup:63,
computeKeyGroupForKeyHash:75, computeOperatorIndexForKeyGroup:124): the same
murmur-mix bit-for-bit, lowered to uint32 jnp ops so routing runs on device
inside shard_map. Parity with the host path (core/keygroups.py) is what makes
checkpoints produced by host subtasks restorable onto device shards and vice
versa.

A subtask index here is a position along the mesh's "data" axis; every device
owns the contiguous key-group range key_group_range_for_operator gives it.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..core.keygroups import KeyGroupRange, key_group_range_for_operator
from ..ops.hash_table import ensure_x64

__all__ = ["make_mesh", "shard_ranges", "murmur_mix_device",
           "hash_int64_device", "key_groups_device",
           "device_index_for_key_groups", "DATA_AXIS"]

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis_name,))


def shard_ranges(max_parallelism: int, n_devices: int,
                 base: Optional[KeyGroupRange] = None
                 ) -> list[KeyGroupRange]:
    """Key-group range owned by each mesh position. With ``base``, the
    devices split THAT subtask range instead of the full key space — the
    two-level split of SURVEY §5.8: a multi-host job partitions key groups
    across host subtasks over DCN (standard operator-index math), and each
    host's local mesh re-partitions its subtask range across its devices
    over ICI, with the same reference rounding rules applied in local
    coordinates.

    Remainder handling (max_parallelism % n_devices != 0): the reference
    rounding (KeyGroupRangeAssignment.java:computeKeyGroupRangeForOperatorIndex)
    gives device i the range [ceil(i*MP/n), floor(((i+1)*MP - 1)/n)], so
    consecutive ranges are CONTIGUOUS (next start = previous end + 1) and
    together cover [0, MP) exactly, with sizes differing by at most one —
    never an even-split truncation that would orphan the last MP % n key
    groups. The same holds in local coordinates under ``base``. Both
    invariants, plus agreement with device_index_for_key_groups routing,
    are pinned by the property test in tests/test_parallel.py. Every range
    must be non-empty, so n_devices may not exceed the (base) key-group
    count — that is a configuration error reported here rather than an
    opaque KeyGroupRange validation failure."""
    if base is None:
        if max_parallelism < n_devices:
            raise ValueError(
                f"max_parallelism {max_parallelism} < {n_devices} devices "
                f"leaves some devices without key groups; raise "
                f"pipeline.max-parallelism or shrink the mesh")
        return [key_group_range_for_operator(max_parallelism, n_devices, i)
                for i in range(n_devices)]
    length = base.end - base.start + 1
    if length < n_devices:
        raise ValueError(
            f"subtask key-group range {base} has {length} groups < "
            f"{n_devices} devices; raise pipeline.max-parallelism")
    out = []
    for i in range(n_devices):
        r = key_group_range_for_operator(length, n_devices, i)
        out.append(KeyGroupRange(base.start + r.start, base.start + r.end))
    return out


def _rotl32(x: jax.Array, r: int) -> jax.Array:
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def murmur_mix_device(code: jax.Array) -> jax.Array:
    """Device twin of core.keygroups.murmur_mix (uint32 -> non-negative
    int32), byte-identical to the host path."""
    k = code.astype(jnp.uint32)
    k = k * jnp.uint32(0xCC9E2D51)
    k = _rotl32(k, 15)
    k = k * jnp.uint32(0x1B873593)
    h = _rotl32(k, 13)
    h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    h = h ^ jnp.uint32(4)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    out = h.astype(jnp.int32)
    return jnp.where(out == jnp.int32(-2147483648), jnp.int32(0),
                     jnp.abs(out))


def hash_int64_device(keys: jax.Array) -> jax.Array:
    """Device twin of core.keygroups.hash_batch's integer fast path
    (Long.hashCode fold: v ^ (v >>> 32))."""
    ensure_x64()
    u = keys.astype(jnp.int64).view(jnp.uint64)
    return ((u ^ (u >> jnp.uint64(32)))
            & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)


def key_groups_device(keys: jax.Array, max_parallelism: int) -> jax.Array:
    """int64 keys -> int32 key groups, matching assign_to_key_group."""
    return murmur_mix_device(hash_int64_device(keys)) % jnp.int32(
        max_parallelism)


def device_index_for_key_groups(key_groups: jax.Array, n_devices: int,
                                max_parallelism: int,
                                base_start: int = 0,
                                base_len: Optional[int] = None) -> jax.Array:
    """Device twin of operator_index_for_key_group: kg * p // maxp.
    ``base_start``/``base_len`` scope the routing to a subtask's key-group
    range (two-level split; see shard_ranges)."""
    length = max_parallelism if base_len is None else base_len
    return ((key_groups - jnp.int32(base_start))
            * jnp.int32(n_devices)) // jnp.int32(length)
