"""Multi-chip execution: mesh topology, keyBy all-to-all exchange, sharded
keyed-window aggregation (SURVEY.md §2.10 / §5.8 — the ICI-collective
replacement for the reference's KeyGroupStreamPartitioner + Netty stack)."""

from .exchange import (ExchangePlan, bucket_capacity, exchange_round,
                       keyby_exchange, plan_exchange)
from .mesh import (DATA_AXIS, device_index_for_key_groups, hash_int64_device,
                   key_groups_device, make_mesh, murmur_mix_device,
                   shard_ranges)
from .plan import (DECLARED_AXES, MESH_RUNTIME, AxisRule, ShardingPlan,
                   parse_axis_rules)
from .rescale import MigrationPlan, paginate_snapshot, plan_migration
from .sharded_window import (AggDef, ShardedWindowAgg, ShardedWindowState,
                             global_topk)

__all__ = [
    "DATA_AXIS", "make_mesh", "shard_ranges", "murmur_mix_device",
    "hash_int64_device", "key_groups_device", "device_index_for_key_groups",
    "keyby_exchange", "plan_exchange", "exchange_round", "ExchangePlan",
    "bucket_capacity", "AggDef", "ShardedWindowAgg", "ShardedWindowState",
    "global_topk", "ShardingPlan", "AxisRule", "parse_axis_rules",
    "DECLARED_AXES", "MESH_RUNTIME", "MigrationPlan", "paginate_snapshot",
    "plan_migration",
]
