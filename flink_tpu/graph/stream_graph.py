"""StreamGraph and JobGraph: graph compilation with operator chaining.

Analog of the reference's two-stage translation
(flink-streaming-java api/graph/StreamGraphGenerator.java:136 generate():320
and StreamingJobGraphGenerator.java:129 createJobGraph:136): the
Transformation DAG flattens into a StreamGraph (nodes + partitioned edges;
unions dissolve into plain edges), then chainable runs fuse into JobVertices.

A chained JobVertex is the TPU fusion unit: all its operators execute in one
task, and when all are jax-traceable the whole chain compiles into one XLA
program. Chaining rule (reference StreamingJobGraphGenerator.isChainable):
forward edge + equal parallelism + single in-edge + chaining enabled on both
nodes + same slot-sharing group.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.config import Configuration, PipelineOptions
from .transformations import (
    FeedbackTransformation, OneInputTransformation, PartitionTransformation,
    SideOutputTransformation, SinkTransformation, SourceTransformation,
    Transformation, TwoInputTransformation, UnionTransformation,
)

__all__ = ["StreamNode", "StreamEdge", "StreamGraph", "JobVertex", "JobEdge",
           "JobGraph", "build_stream_graph", "build_job_graph"]


@dataclass
class StreamNode:
    id: int
    name: str
    kind: str  # source | one_input | two_input | sink
    parallelism: int
    max_parallelism: int
    uid: str = ""
    uid_explicit: bool = False  # user-set via .uid(), vs generated
    chaining_allowed: bool = True
    slot_sharing_group: str = "default"
    operator_factory: Optional[Callable] = None
    key_extractor: Optional[Callable] = None
    key_extractor2: Optional[Callable] = None
    source: Any = None
    watermark_strategy: Any = None
    traceable: bool = False
    # iteration head (FeedbackTransformation): its gate terminates after
    # regular inputs end + the feedback loop stays quiet for this long
    iteration_head: bool = False
    iteration_wait_s: float = 0.0


@dataclass
class StreamEdge:
    source_id: int
    target_id: int
    partitioner_factory: Callable[[], Any]
    partitioner_name: str = "forward"
    side_tag: Optional[str] = None
    target_input: int = 0  # 0/1 for two-input operators
    feedback: bool = False  # iteration back edge (body tail -> head)


@dataclass
class StreamGraph:
    nodes: dict[int, StreamNode] = field(default_factory=dict)
    edges: list[StreamEdge] = field(default_factory=list)

    def in_edges(self, node_id: int) -> list[StreamEdge]:
        return [e for e in self.edges if e.target_id == node_id]

    def out_edges(self, node_id: int) -> list[StreamEdge]:
        return [e for e in self.edges if e.source_id == node_id]


def build_stream_graph(sinks: list[Transformation],
                       config: Configuration) -> StreamGraph:
    """Flatten the transformation DAG; virtual nodes (partition/union/side
    output) dissolve into edge attributes (reference StreamGraphGenerator
    virtual transformations)."""
    g = StreamGraph()
    default_par = config.get(PipelineOptions.DEFAULT_PARALLELISM)
    default_maxp = config.get(PipelineOptions.MAX_PARALLELISM)
    visited: dict[int, int] = {}  # transformation id -> stream node id

    def resolve_input(t: Transformation) -> list[tuple[int, dict]]:
        """Resolve a transformation to (upstream node id, edge attrs) pairs,
        dissolving virtual nodes."""
        if isinstance(t, PartitionTransformation):
            out = []
            for up in t.inputs:
                for nid, attrs in resolve_input(up):
                    a = dict(attrs)
                    a["partitioner_factory"] = t.partitioner_factory
                    a["partitioner_name"] = t.partitioner_name
                    out.append((nid, a))
            return out
        if isinstance(t, UnionTransformation):
            out = []
            for up in t.inputs:
                out.extend(resolve_input(up))
            return out
        if isinstance(t, SideOutputTransformation):
            out = []
            for up in t.inputs:
                for nid, attrs in resolve_input(up):
                    a = dict(attrs)
                    a["side_tag"] = t.tag
                    out.append((nid, a))
            return out
        return [(visit(t), {})]

    def visit(t: Transformation) -> int:
        if t.id in visited:
            return visited[t.id]
        if isinstance(t, (PartitionTransformation, UnionTransformation,
                          SideOutputTransformation)):
            raise AssertionError("virtual nodes resolve through resolve_input")

        par = t.parallelism or default_par
        maxp = t.max_parallelism or default_maxp
        if isinstance(t, SourceTransformation):
            node = StreamNode(t.id, t.name, "source", par, maxp,
                              uid=t.effective_uid,
                              uid_explicit=t.uid is not None,
                              chaining_allowed=t.chaining_allowed,
                              slot_sharing_group=t.slot_sharing_group,
                              source=t.source,
                              watermark_strategy=t.watermark_strategy)
        elif isinstance(t, SinkTransformation):
            node = StreamNode(t.id, t.name, "sink", par, maxp,
                              uid=t.effective_uid,
                              uid_explicit=t.uid is not None,
                              chaining_allowed=t.chaining_allowed,
                              slot_sharing_group=t.slot_sharing_group,
                              operator_factory=t.operator_factory)
        elif isinstance(t, TwoInputTransformation):
            node = StreamNode(t.id, t.name, "two_input", par, maxp,
                              uid=t.effective_uid,
                              uid_explicit=t.uid is not None,
                              chaining_allowed=t.chaining_allowed,
                              slot_sharing_group=t.slot_sharing_group,
                              operator_factory=t.operator_factory,
                              key_extractor=t.key_extractor1,
                              key_extractor2=t.key_extractor2)
        elif isinstance(t, FeedbackTransformation):
            from ..runtime.operators.simple import BatchFnOperator
            node = StreamNode(t.id, t.name, "one_input", par, maxp,
                              uid=t.effective_uid,
                              uid_explicit=t.uid is not None,
                              # the head owns a special gate: never fuse it
                              # into an upstream chain (a source task has
                              # no gate to attach the feedback channel to)
                              chaining_allowed=False,
                              slot_sharing_group=t.slot_sharing_group,
                              operator_factory=lambda: BatchFnOperator(
                                  lambda b: b, "IterationHead"),
                              iteration_head=True,
                              iteration_wait_s=t.max_wait_s)
        elif isinstance(t, OneInputTransformation):
            node = StreamNode(t.id, t.name, "one_input", par, maxp,
                              uid=t.effective_uid,
                              uid_explicit=t.uid is not None,
                              chaining_allowed=t.chaining_allowed,
                              slot_sharing_group=t.slot_sharing_group,
                              operator_factory=t.operator_factory,
                              key_extractor=t.key_extractor,
                              traceable=t.traceable)
        else:
            raise TypeError(f"Unknown transformation {type(t)}")
        g.nodes[node.id] = node
        # register BEFORE resolving inputs: a feedback edge cycles back to
        # this node, and the visited entry is what breaks the recursion
        visited[t.id] = node.id

        if isinstance(t, TwoInputTransformation):
            for input_idx, up in enumerate(t.inputs):
                for nid, attrs in resolve_input(up):
                    g.edges.append(_make_edge(nid, node.id, attrs, input_idx))
        else:
            for up in t.inputs:
                for nid, attrs in resolve_input(up):
                    g.edges.append(_make_edge(nid, node.id, attrs, 0))
        if isinstance(t, FeedbackTransformation):
            if not t.feedback_inputs:
                raise ValueError(
                    f"iteration {t.name!r} was never closed: call "
                    "close_with(feedback_stream) on the IterativeStream")
            for up in t.feedback_inputs:
                for nid, attrs in resolve_input(up):
                    a = dict(attrs)
                    a["feedback"] = True
                    g.edges.append(_make_edge(nid, node.id, a, 0))
        return node.id

    for s in sinks:
        visit(s)
    return g


def _make_edge(source_id: int, target_id: int, attrs: dict,
               target_input: int) -> StreamEdge:
    from ..runtime.writer import ForwardPartitioner
    return StreamEdge(
        source_id, target_id,
        partitioner_factory=attrs.get("partitioner_factory",
                                      ForwardPartitioner),
        partitioner_name=attrs.get("partitioner_name", "forward"),
        side_tag=attrs.get("side_tag"),
        target_input=target_input,
        feedback=attrs.get("feedback", False))


# ---------------------------------------------------------------------------
# JobGraph: chained vertices
# ---------------------------------------------------------------------------

@dataclass
class JobEdge:
    source_vertex: str
    target_vertex: str
    partitioner_factory: Callable[[], Any]
    partitioner_name: str = "forward"
    side_tag: Optional[str] = None
    target_input: int = 0
    feedback: bool = False


@dataclass
class JobVertex:
    id: str
    name: str
    parallelism: int
    max_parallelism: int
    chained_nodes: list[StreamNode] = field(default_factory=list)
    slot_sharing_group: str = "default"
    # stable across job submissions: user-set uid, or an auto uid derived
    # from the vertex's position + chain names (reference auto-generated
    # operator ids hash the topology for the same reason) — the key
    # savepoint restore maps operators by
    uid: str = ""

    @property
    def kind(self) -> str:
        return self.chained_nodes[0].kind

    @property
    def is_traceable_chain(self) -> bool:
        return all(n.traceable for n in self.chained_nodes
                   if n.kind == "one_input")


@dataclass
class JobGraph:
    name: str
    vertices: dict[str, JobVertex] = field(default_factory=dict)
    edges: list[JobEdge] = field(default_factory=list)
    config: Configuration = field(default_factory=Configuration)
    # FusionCertificate attached by the environment when
    # pipeline.fusion.enabled — deploy reads lowered_prefix per vertex
    certificate: Any = None

    def in_edges(self, vid: str) -> list[JobEdge]:
        return [e for e in self.edges if e.target_vertex == vid]

    def out_edges(self, vid: str) -> list[JobEdge]:
        return [e for e in self.edges if e.source_vertex == vid]

    def topological_order(self) -> list[JobVertex]:
        order, seen = [], set()

        def dfs(vid: str):
            if vid in seen:
                return
            seen.add(vid)
            for e in self.in_edges(vid):
                dfs(e.source_vertex)
            order.append(self.vertices[vid])

        for vid in self.vertices:
            dfs(vid)
        return order


def build_job_graph(g: StreamGraph, config: Configuration,
                    name: str = "job") -> JobGraph:
    chaining = config.get(PipelineOptions.CHAINING_ENABLED)
    fusion = config.get(PipelineOptions.FUSION)
    _window_head: dict[int, bool] = {}

    def device_window_head(node: StreamNode) -> bool:
        """Does this node's factory build a device window aggregate?
        (Instantiation is cheap: backend creation lives in setup().)"""
        if node.id not in _window_head:
            ok = False
            if node.kind == "one_input" and node.operator_factory is not None:
                try:
                    from ..runtime.operators.device_window import (
                        DeviceWindowAggOperator,
                    )
                    ok = isinstance(node.operator_factory(),
                                    DeviceWindowAggOperator)
                except Exception:
                    ok = False
            _window_head[node.id] = ok
        return _window_head[node.id]

    def chainable(e: StreamEdge) -> bool:
        if not chaining or e.side_tag is not None or e.feedback:
            return False
        up, down = g.nodes[e.source_id], g.nodes[e.target_id]
        forward_ok = e.partitioner_name == "forward"
        if not forward_ok and fusion:
            # whole-chain fusion: a hash exchange at parallelism 1 is
            # forward-equivalent (every record lands on subtask 0), so
            # the keyed edge into a device window aggregate may chain —
            # that is what lets a certified source -> window prefix
            # lower to one dispatch (graph/fusion.py)
            forward_ok = (e.partitioner_name == "hash"
                          and up.parallelism == 1
                          and down.parallelism == 1
                          and device_window_head(down))
        return (forward_ok
                and up.parallelism == down.parallelism
                and up.slot_sharing_group == down.slot_sharing_group
                and down.kind in ("one_input", "sink")
                and down.chaining_allowed and up.chaining_allowed
                and len(g.in_edges(down.id)) == 1
                and len(g.out_edges(up.id)) == 1)

    # map each stream node to the head of its chain
    head_of: dict[int, int] = {}
    for nid in g.nodes:
        head = nid
        while True:
            ins = g.in_edges(head)
            if len(ins) == 1 and chainable(ins[0]):
                head = ins[0].source_id
            else:
                break
        head_of[nid] = head

    jg = JobGraph(name=name, config=config)
    # build chains in order
    auto_uid_counts: dict[str, int] = {}
    for nid, node in g.nodes.items():
        if head_of[nid] != nid:
            continue
        chain = [node]
        cur = nid
        while True:
            outs = g.out_edges(cur)
            if len(outs) == 1 and chainable(outs[0]):
                cur = outs[0].target_id
                chain.append(g.nodes[cur])
            else:
                break
        head = chain[0]
        vid = f"v{head.id}"
        chain_name = " -> ".join(n.name for n in chain)
        if head.uid_explicit:
            uid = head.uid  # explicitly set by the user
        else:
            # auto uid stable across submissions of the same program:
            # chain shape + occurrence index (transformation ids are a
            # process-global counter and would NOT survive resubmission)
            idx = auto_uid_counts.get(chain_name, 0)
            auto_uid_counts[chain_name] = idx + 1
            uid = f"auto::{chain_name}::{idx}"
        jg.vertices[vid] = JobVertex(
            id=vid,
            name=chain_name,
            parallelism=head.parallelism,
            max_parallelism=head.max_parallelism,
            chained_nodes=chain,
            slot_sharing_group=head.slot_sharing_group,
            uid=uid)

    # edges between chains
    for e in g.edges:
        src_head, dst_head = head_of[e.source_id], head_of[e.target_id]
        if src_head == dst_head:
            continue  # intra-chain edge, consumed by chaining
        jg.edges.append(JobEdge(
            source_vertex=f"v{src_head}", target_vertex=f"v{dst_head}",
            partitioner_factory=e.partitioner_factory,
            partitioner_name=e.partitioner_name,
            side_tag=e.side_tag, target_input=e.target_input,
            feedback=e.feedback))
    return jg
