"""Fusion certifier: graph-level static analysis of the JobGraph that
proves which operator chains are lowerable to ONE XLA dispatch — and
names precisely why the rest are not.

The StreamGraph docstring has long asserted "when all are jax-traceable
the whole chain compiles into one XLA program"; this module is the
proof obligation behind that claim. ``certify`` walks every chained
JobVertex, classifies each operator's device-safety, and emits a
:class:`FusionCertificate` naming the maximal legal fusable sub-chains
("runs"). Every boundary that *rejects* fusion — a host-effectful op, a
serializer/schema boundary, a shuffle where a forward edge was
possible, a timer/side-output escape — produces a PLAN6xx finding that
`analysis/plan_rules.py` surfaces through the tpu-lint gate.

Legal flush points (never findings): sinks, keyed exchanges into
keyed-stateful operators, and the coalescing flush points
(watermark/barrier/schema-change) that already bound a fused dispatch.

The runtime consumes the certificate: ``cluster/local.py`` lowers a
certified ``source-decode -> window-step`` prefix (tiny Q5's shape)
into a single donated program (``runtime/compiled.py``), and Tier-B
rules JX601-603 audit the programs that lowering produces.
"""

from __future__ import annotations

import inspect
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

__all__ = ["VERDICTS", "PlanFinding", "FusedOp", "ChainReport",
           "FusionCertificate", "certify", "CERTIFICATE_LOG",
           "capture_certificates", "exercise_certificates"]

# Certificate verdict vocabulary — doc-locked against docs/ANALYSIS.md
# (tests/test_fusion.py asserts the table there lists exactly these).
VERDICTS = ("CERTIFIED", "PARTIAL", "REJECTED")

# Operator categories. Fusable: a run may start at a device source and
# extend through pure ops; a device window aggregate certifies as the
# run's keyed partial-agg tail. Flush categories end a run legally.
_FUSABLE = ("source-device", "pure")
_FLUSH = ("sink", "keyed-device", "keyed-host", "window-device",
          "source-host", "two-input")
_CUTTER_RULE = {"host-effectful": "PLAN601", "serializer": "PLAN602",
                "timer": "PLAN604", "unknown": "PLAN601"}


@dataclass(frozen=True)
class PlanFinding:
    """One rejected fusion boundary, anchored to the operator class."""
    rule: str
    message: str
    file: str       # repo-relative posix path of the rejecting op class
    line: int
    symbol: str     # "<vertex uid>:<node name>" — stable across edits


@dataclass
class FusedOp:
    node_id: int
    name: str
    category: str
    detail: str
    file: str
    line: int


@dataclass
class ChainReport:
    vertex_id: str
    uid: str
    name: str
    parallelism: int
    ops: list[FusedOp] = field(default_factory=list)
    verdict: str = "REJECTED"
    # maximal legal fusable sub-chains, as lists of stream-node ids
    certified: list[list[int]] = field(default_factory=list)
    # the prefix the runtime will actually lower to one dispatch
    # (source -> device window, parallelism 1, fusion enabled)
    lowered_prefix: list[int] = field(default_factory=list)
    findings: list[PlanFinding] = field(default_factory=list)

    def op(self, node_id: int) -> Optional[FusedOp]:
        for o in self.ops:
            if o.node_id == node_id:
                return o
        return None


@dataclass
class FusionCertificate:
    job_name: str
    fusion_enabled: bool
    chains: list[ChainReport] = field(default_factory=list)

    def findings(self) -> list[PlanFinding]:
        out = []
        for c in self.chains:
            out.extend(c.findings)
        return out

    def chain_for_vertex(self, vertex_id: str) -> Optional[ChainReport]:
        for c in self.chains:
            if c.vertex_id == vertex_id:
                return c
        return None

    def to_dict(self) -> dict:
        return {
            "job": self.job_name,
            "fusion_enabled": self.fusion_enabled,
            "chains": [{
                "vertex": c.vertex_id, "uid": c.uid, "name": c.name,
                "parallelism": c.parallelism, "verdict": c.verdict,
                "ops": [{"node": o.node_id, "name": o.name,
                         "category": o.category, "detail": o.detail,
                         "location": f"{o.file}:{o.line}"} for o in c.ops],
                "certified": c.certified,
                "lowered_prefix": c.lowered_prefix,
                "findings": [{"rule": f.rule, "message": f.message,
                              "location": f"{f.file}:{f.line}",
                              "symbol": f.symbol} for f in c.findings],
            } for c in self.chains],
        }


# Recent certificates, newest last — populated by every certify() call.
# analysis/plan_rules.py reads this; tests seed it directly.
CERTIFICATE_LOG: deque = deque(maxlen=64)


# ---------------------------------------------------------------------------
# Classification


def _repo_rel(path: Optional[str]) -> str:
    if not path:
        return "<unknown>"
    p = Path(path)
    for parent in p.parents:
        if parent.name == "flink_tpu":
            return p.relative_to(parent.parent).as_posix()
    return p.name


def _class_location(cls: type) -> tuple[str, int]:
    try:
        f = inspect.getsourcefile(cls)
        line = inspect.getsourcelines(cls)[1]
        return _repo_rel(f), line
    except (OSError, TypeError):
        return "<unknown>", 0


def _classify_operator(op: Any) -> tuple[str, str]:
    """Device-safety category of an instantiated operator. Reuses the
    same class facts Tier A keys on: vectorized batch methods are the
    jax-traceable surface; row loops decode host rows (a serializer
    boundary); timers and side collectors escape the dispatch."""
    from ..runtime.operators.device_window import DeviceWindowAggOperator
    from ..runtime.operators.simple import (
        BatchFnOperator, FilterOperator, FlatMapOperator, KeyedProcessOperator,
        MapOperator,
    )

    if isinstance(op, DeviceWindowAggOperator):
        return "window-device", "keyed partial-agg tail (one-dispatch step)"
    mod = type(op).__module__
    name = type(op).__name__
    if name in ("DeviceSessionWindowOperator", "MeshWindowAggOperator",
                "DeviceGroupAggOperator"):
        return "keyed-device", "keyed device aggregate (own fused step)"
    if isinstance(op, (KeyedProcessOperator,)) or name in (
            "CepOperator", "AsyncWaitOperator", "WindowOperator"):
        return "timer", "timer/side-output surface escapes the dispatch"
    if isinstance(op, BatchFnOperator):
        if getattr(op, "traceable", False):
            return "pure", "jax-traceable columnwise batch fn"
        return "host-effectful", "opaque batch fn (not declared traceable)"
    if isinstance(op, MapOperator):
        from ..core.functions import MapFunction
        fn = getattr(op, "_fn", None)
        if fn is not None and \
                type(fn).map_batch is not MapFunction.map_batch:
            return "pure", "vectorized map_batch"
        return "serializer", "row-loop map decodes host rows"
    if isinstance(op, FilterOperator):
        from ..core.functions import FilterFunction
        fn = getattr(op, "_fn", None)
        if fn is not None and \
                type(fn).filter_batch is not FilterFunction.filter_batch:
            return "pure", "vectorized filter_batch"
        return "serializer", "row-loop filter decodes host rows"
    if isinstance(op, FlatMapOperator):
        return "serializer", "row-loop flat_map decodes host rows"
    if mod.startswith("flink_tpu.sql"):
        return "keyed-host", "host keyed SQL operator (legal flush point)"
    return "host-effectful", f"unclassified operator {name}"


def _classify_node(node: Any) -> FusedOp:
    """StreamNode -> FusedOp. Instantiating the factory is safe for the
    operators we classify (heavy setup lives in setup()/open())."""
    if node.kind == "source":
        src = node.source
        file, line = _class_location(type(src))
        if getattr(src, "_device", getattr(src, "device", False)):
            return FusedOp(node.id, node.name, "source-device",
                           "device-resident generator batches", file, line)
        return FusedOp(node.id, node.name, "source-host",
                       "host-resident source batches", file, line)
    if node.kind == "sink":
        cat, detail = "sink", "chain flush point"
    elif node.kind == "two_input":
        cat, detail = "two-input", "two-input barrier"
    elif node.traceable:
        cat, detail = "pure", "declared jax-traceable"
    else:
        cat, detail = "unknown", "operator factory failed to classify"
    if node.kind == "one_input" and node.operator_factory is not None:
        try:
            op = node.operator_factory()
            c, d = _classify_operator(op)
            file, line = _class_location(type(op))
            if node.traceable and c in ("host-effectful", "serializer",
                                        "unknown"):
                c, d = "pure", "declared jax-traceable"
            return FusedOp(node.id, node.name, c, d, file, line)
        except Exception as e:  # classification must never kill compile
            return FusedOp(node.id, node.name, "unknown",
                           f"factory raised during classification: {e!r}",
                           "<unknown>", 0)
    if node.kind == "sink" and node.operator_factory is not None:
        try:
            file, line = _class_location(type(node.operator_factory()))
        except Exception:
            file, line = "<unknown>", 0
        return FusedOp(node.id, node.name, cat, detail, file, line)
    return FusedOp(node.id, node.name, cat, detail, "<unknown>", 0)


# ---------------------------------------------------------------------------
# Certification


def _walk_chain(report: ChainReport, side_tagged: set[int]) -> None:
    """Split a chained vertex into maximal fusable runs; every run cut
    by a non-flush category is a rejected boundary -> PLAN finding."""
    run: list[FusedOp] = []

    def close(cutter: Optional[FusedOp], rule: Optional[str]) -> None:
        nonlocal run
        if len(run) >= 2:
            report.certified.append([o.node_id for o in run])
            if cutter is not None and rule is not None:
                report.findings.append(PlanFinding(
                    rule=rule,
                    message=(f"fusable run [{', '.join(o.name for o in run)}]"
                             f" is cut by {cutter.name!r}: {cutter.detail}"),
                    file=cutter.file, line=cutter.line,
                    symbol=f"{report.uid}:{cutter.name}"))
        run = []

    for op in report.ops:
        if op.node_id in side_tagged and run:
            # a side output escapes the candidate fused region: records
            # leave mid-dispatch, so the run ends here (PLAN604)
            report.findings.append(PlanFinding(
                rule="PLAN604",
                message=(f"side output escapes the fusable run at "
                         f"{op.name!r}; fusion stops at the tag"),
                file=op.file, line=op.line,
                symbol=f"{report.uid}:{op.name}:side"))
            close(None, None)
        if op.category in _FUSABLE:
            if op.category == "source-device" and run:
                close(None, None)  # defensive: sources only head a chain
            run.append(op)
            continue
        if op.category == "window-device":
            # certified keyed partial-agg tail — its own one-dispatch
            # step even when nothing fusable precedes it
            run.append(op)
            report.certified.append([o.node_id for o in run])
            run = []
            continue
        if op.category in _FLUSH:
            close(None, None)    # legal flush point, no finding
            continue
        close(op, _CUTTER_RULE.get(op.category, "PLAN601"))
    close(None, None)

    # Verdict: CERTIFIED = every boundary in the chain is a legal flush
    # point (findings name the rejected ones); PARTIAL = rejected
    # boundaries exist but some run still certified; REJECTED = rejected
    # boundaries and nothing certified.
    if report.findings:
        report.verdict = "PARTIAL" if report.certified else "REJECTED"
    else:
        report.verdict = "CERTIFIED"


def certify(stream_graph: Any, job_graph: Any,
            config: Any = None) -> FusionCertificate:
    """Build the fusion certificate for a compiled job. Pure analysis —
    never mutates either graph; the result is appended to
    ``CERTIFICATE_LOG`` and (when fusion is enabled) attached to the
    JobGraph by the environment for the deploy-time lowering."""
    from ..core.config import PipelineOptions
    enabled = bool(config.get(PipelineOptions.FUSION)) if config is not None \
        else False
    cert = FusionCertificate(job_name=getattr(job_graph, "name", "job"),
                             fusion_enabled=enabled)

    side_tagged = {e.source_id for e in stream_graph.edges
                   if e.side_tag is not None}

    for vid, vertex in job_graph.vertices.items():
        report = ChainReport(vertex_id=vid, uid=vertex.uid,
                             name=vertex.name,
                             parallelism=vertex.parallelism)
        for node in vertex.chained_nodes:
            report.ops.append(_classify_node(node))
        _walk_chain(report, side_tagged)
        # runtime lowering: a certified run that starts at the device
        # source heading this vertex and ends at a DeviceWindowAggOperator
        # lowers to one dispatch (parallelism 1 only — the keyed exchange
        # it absorbs is forward-equivalent at a single subtask)
        if enabled and vertex.parallelism == 1 and report.certified:
            head_run = report.certified[0]
            ops_by_id = {o.node_id: o for o in report.ops}
            first, last = ops_by_id[head_run[0]], ops_by_id[head_run[-1]]
            if (first.node_id == vertex.chained_nodes[0].id
                    and first.category == "source-device"
                    and last.category == "window-device"):
                report.lowered_prefix = list(head_run)
        cert.chains.append(report)

    # PLAN603: a shuffle (non-forward exchange) between two operators
    # that would otherwise fuse — the boundary costs a dispatch + a
    # serialize/partition round-trip that a forward edge would not.
    for e in job_graph.edges:
        if e.side_tag is not None:
            continue
        src = cert.chain_for_vertex(e.source_vertex)
        dst = cert.chain_for_vertex(e.target_vertex)
        if src is None or dst is None or not src.ops or not dst.ops:
            continue
        tail, head = src.ops[-1], dst.ops[0]
        keyed_into_state = (e.partitioner_name == "hash"
                            and head.category in ("window-device",
                                                  "keyed-device",
                                                  "keyed-host", "timer"))
        if keyed_into_state:
            continue  # the keyed exchange IS the legal flush point
        if (e.partitioner_name != "forward" or e.feedback) \
                and tail.category in _FUSABLE \
                and head.category in ("pure",) \
                and src.parallelism == dst.parallelism:
            chain = dst if head.category == "pure" else src
            chain.findings.append(PlanFinding(
                rule="PLAN603",
                message=(f"non-forward edge ({e.partitioner_name}"
                         f"{', feedback' if e.feedback else ''}) between "
                         f"fusable operators {tail.name!r} -> {head.name!r} "
                         "at equal parallelism: a forward edge would fuse"),
                file=head.file, line=head.line,
                symbol=f"{dst.uid}:{head.name}:edge"))
            chain.verdict = "PARTIAL" if chain.certified else "REJECTED"

    CERTIFICATE_LOG.append(cert)
    return cert


# ---------------------------------------------------------------------------
# Capture harness: certify example pipelines without running them


class _Absorb:
    """Duck-typed stand-in for a job/result: every attribute is a no-op
    callable that returns another absorber, so example scripts survive
    result plumbing after a stubbed execute."""

    def __call__(self, *a, **k):
        return self

    def __getattr__(self, _name):
        return self

    def __iter__(self):
        return iter(())

    def __bool__(self):
        return False


def capture_certificates(path: str, argv: Optional[list] = None
                         ) -> tuple[list[FusionCertificate], Optional[str]]:
    """Run an example script (or a .sql file through the Table API) with
    execution stubbed out: every execute()/submit() compiles the graphs,
    certifies them, and returns a dummy. Returns (certificates, error) —
    ``error`` is the tolerated script failure, if any, once capture ran."""
    import runpy
    import sys

    from ..api.environment import StreamExecutionEnvironment

    captured: list[FusionCertificate] = []

    def _capture(env) -> None:
        from ..graph.stream_graph import build_job_graph, build_stream_graph
        sg = build_stream_graph(env._sinks, env.config)
        jg = build_job_graph(sg, env.config)
        captured.append(certify(sg, jg, env.config))
        env._transformations = []
        env._sinks = []

    def fake_execute(self, *a, **k):
        _capture(self)
        return _Absorb()

    def fake_submit(self, env, *a, **k):
        _capture(env)
        return "captured-job"

    patches = [(StreamExecutionEnvironment, "execute", fake_execute),
               (StreamExecutionEnvironment, "execute_async", fake_execute)]
    try:
        from ..cluster.dispatcher import ClusterClient, Dispatcher
        patches.append((ClusterClient, "submit", fake_submit))
        patches.append((ClusterClient, "wait",
                        lambda self, *a, **k: _Absorb()))
        patches.append((Dispatcher, "start", lambda self, *a, **k: 0))
    except ImportError:  # pragma: no cover
        pass

    saved = [(cls, name, getattr(cls, name)) for cls, name, _ in patches]
    for cls, name, fn in patches:
        setattr(cls, name, fn)
    old_argv = sys.argv
    error: Optional[str] = None
    try:
        sys.argv = [str(path)] + list(argv or [])
        if str(path).endswith(".sql"):
            from ..sql.table_env import TableEnvironment
            t_env = TableEnvironment.create()
            for stmt in Path(path).read_text().split(";"):
                if stmt.strip():
                    t_env.execute_sql(stmt)
        else:
            runpy.run_path(str(path), run_name="__main__")
    except SystemExit as e:
        if e.code not in (0, None):
            error = f"SystemExit({e.code})"
    except BaseException as e:  # tolerated once capture ran
        error = f"{type(e).__name__}: {e}"
    finally:
        sys.argv = old_argv
        for cls, name, fn in saved:
            setattr(cls, name, fn)
    return captured, error


def exercise_certificates(examples_dir: Optional[Path] = None
                          ) -> list[FusionCertificate]:
    """Certify every example pipeline (the lint gate's corpus when no
    certificates were captured in-process)."""
    if examples_dir is None:
        examples_dir = Path(__file__).resolve().parent.parent.parent \
            / "examples"
    out: list[FusionCertificate] = []
    if not examples_dir.is_dir():
        return out
    for p in sorted(examples_dir.glob("*.py")):
        certs, _err = capture_certificates(str(p))
        out.extend(certs)
    return out
