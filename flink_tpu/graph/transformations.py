"""Transformation DAG: what the fluent API records.

Analog of flink-core/streaming transformations
(api/dag/Transformation, flink-streaming-java transformations/
OneInputTransformation, PartitionTransformation, SourceTransformation,
SinkTransformation, UnionTransformation): a lazy DAG the environment
translates into a StreamGraph (graph/stream_graph.py).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from ..core.records import Schema
from ..core.watermarks import WatermarkStrategy

__all__ = [
    "Transformation", "SourceTransformation", "OneInputTransformation",
    "TwoInputTransformation", "PartitionTransformation", "UnionTransformation",
    "SinkTransformation", "SideOutputTransformation",
]

_ids = itertools.count(1)


@dataclass
class Transformation:
    name: str
    parallelism: Optional[int] = None
    max_parallelism: Optional[int] = None
    schema: Optional[Schema] = None
    inputs: list["Transformation"] = field(default_factory=list)
    id: int = field(default_factory=lambda: next(_ids))
    chaining_allowed: bool = True
    slot_sharing_group: str = "default"
    uid: Optional[str] = None  # stable operator id for savepoint mapping

    @property
    def effective_uid(self) -> str:
        return self.uid or f"op-{self.id}"


@dataclass
class SourceTransformation(Transformation):
    source: Any = None
    watermark_strategy: WatermarkStrategy = field(
        default_factory=WatermarkStrategy.no_watermarks)


@dataclass
class OneInputTransformation(Transformation):
    """operator_factory() -> OneInputOperator (fresh instance per subtask)."""

    operator_factory: Callable[[], Any] = None
    # keyed inputs: extractor recomputed downstream for state addressing
    key_extractor: Optional[Callable] = None
    traceable: bool = False  # whole operator is jax-traceable (fusable)


@dataclass
class TwoInputTransformation(Transformation):
    operator_factory: Callable[[], Any] = None
    key_extractor1: Optional[Callable] = None
    key_extractor2: Optional[Callable] = None


@dataclass
class PartitionTransformation(Transformation):
    """Repartitioning edge (reference PartitionTransformation): carries a
    partitioner factory so each upstream subtask gets a fresh stateful
    partitioner (round-robin counters etc.)."""

    partitioner_factory: Callable[[], Any] = None
    partitioner_name: str = "forward"


@dataclass
class UnionTransformation(Transformation):
    pass


@dataclass
class SinkTransformation(Transformation):
    operator_factory: Callable[[], Any] = None


@dataclass
class SideOutputTransformation(Transformation):
    tag: str = ""


@dataclass
class FeedbackTransformation(Transformation):
    """Iteration head (reference FeedbackTransformation +
    StreamIterationHead/Tail): a pass-through node whose input set grows a
    FEEDBACK edge at close_with time — records emitted by the loop body
    flow back into this node. The head terminates after its regular inputs
    finish AND the feedback loop has been quiet for ``max_wait_s``
    (reference iteration-head await timeout)."""

    feedback_inputs: list["Transformation"] = field(default_factory=list)
    max_wait_s: float = 2.0
