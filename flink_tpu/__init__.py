"""flink-tpu: a TPU-native stateful stream-processing framework.

A from-scratch re-architecture of Apache Flink's capabilities (see SURVEY.md)
for JAX/XLA/Pallas: keyed state lives on-device as sharded arrays partitioned
by key-group range, records flow as columnar micro-batches, window triggers
fire one compiled segment-reduce over all keys in a subtask's range, and
multi-chip scale-out uses `jax.sharding` meshes with XLA collectives over ICI
instead of point-to-point TCP shuffles.

Layer map (mirrors SURVEY.md §1):
  core/      L0  config, types/records, key groups, watermarks, serde
  api/       L5  DataStream API
  graph/     L5  Transformation DAG -> StreamGraph -> JobGraph (chaining)
  runtime/   L4  step-loop tasks, operators, timers, harness
  state/     L3  state backend SPI: host hashmap + device-resident TPU backend
  window/    L4  assigners/triggers/slice-shared panes
  checkpoint/L2  barriers, coordinator, snapshots, restore/rescale
  parallel/  --  mesh & sharding utilities (ICI collectives)
  ops/       --  XLA/Pallas kernels (segment-reduce, device hash table)
  cluster/   L2  scheduler, minicluster, failover, heartbeats
  sql/       L6  SQL/Table layer compiled to the same stage graph
  metrics/   L9  metric groups + reporters + spans
  cep/       L8  pattern matching
  connectors/L8  sources/sinks
"""

__version__ = "0.1.0"

from .core import *  # noqa: F401,F403
