"""State processor API: offline read / transform / bootstrap of savepoints.

Analog of the reference's flink-state-processing-api
(SavepointReader.java:59, SavepointWriter.java:62, OperatorTransformation):
savepoints are data, not opaque blobs — read keyed state of any operator as
plain (key, namespace, value) records, patch or bootstrap state without
running the streaming job, and write a restorable savepoint.

Operators are addressed by their chain op-key (``"<index>:<OperatorName>"``,
see OperatorChain) within a vertex; ``SavepointInspector.operators()``
enumerates what a savepoint contains, so no guessing is needed.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Iterator, Optional

from ..checkpoint.storage import (
    CompletedCheckpoint, FsCheckpointStorage,
)
from ..core.keygroups import KeyGroupRange, assign_to_key_group
from ..state.heap import HeapKeyedStateBackend

__all__ = ["SavepointReader", "SavepointWriter", "KeyedStateRecord"]


class KeyedStateRecord(tuple):
    """(key, namespace, value) with named access."""

    __slots__ = ()

    def __new__(cls, key, namespace, value):
        return tuple.__new__(cls, (key, namespace, value))

    @property
    def key(self):
        return self[0]

    @property
    def namespace(self):
        return self[1]

    @property
    def value(self):
        return self[2]


def _iter_heap_states(keyed_snapshot: dict, state_name: str,
                      changelog_root: str = None
                      ) -> Iterator[KeyedStateRecord]:
    """Iterate a heap/changelog-kind keyed snapshot's entries.
    ``changelog_root`` resolves root-relative DSTL handle locations (the
    checkpoint directory's /changelog subdir)."""
    snap = keyed_snapshot.get("backend", keyed_snapshot)
    if snap.get("kind") in ("changelog", "changelog-dstl"):
        # materialized base + replayed log = current view; reuse the
        # backend's own replay for fidelity
        from ..state.changelog import ChangelogKeyedStateBackend
        cb = ChangelogKeyedStateBackend(KeyGroupRange(0, (1 << 15) - 1),
                                        1 << 15)
        if changelog_root is not None:
            from ..state.dstl import FsChangelogStorage
            cb._store = FsChangelogStorage(changelog_root)
            cb._writer.store = cb._store
        cb.restore([snap])
        for (key, ns), value in cb.entries(state_name):
            yield KeyedStateRecord(key, ns, value)
        return
    for per_kg in (snap.get("states", {}).get(state_name, {}) or {}).values():
        for kn, value, _expiry in per_kg:
            key, ns = tuple(kn) if isinstance(kn, list) else kn
            yield KeyedStateRecord(key, ns, value)


class SavepointReader:
    """Read an existing savepoint/checkpoint (reference SavepointReader)."""

    def __init__(self, checkpoint: CompletedCheckpoint,
                 changelog_root: str = None):
        self.checkpoint = checkpoint
        # DSTL handles are root-relative (relocatable checkpoints); the
        # changelog store sits beside the chk-N/sp-N dirs
        self.changelog_root = changelog_root

    @staticmethod
    def read(path: str) -> "SavepointReader":
        import os as _os

        directory, _, leaf = path.rstrip("/").rpartition("/")
        storage = FsCheckpointStorage(directory or ".")
        return SavepointReader(storage.load(path),
                               _os.path.join(directory or ".", "changelog"))

    # -- inspection --------------------------------------------------------
    def vertices(self) -> list[str]:
        return sorted({tid.rsplit("#", 1)[0]
                       for tid in self.checkpoint.task_snapshots})

    def operators(self, vertex: Optional[str] = None) -> dict[str, list[str]]:
        """vertex -> chain op keys present in the savepoint."""
        out: dict[str, set] = {}
        for tid, snap in self.checkpoint.task_snapshots.items():
            vid = tid.rsplit("#", 1)[0]
            if vertex is not None and vid != vertex:
                continue
            out.setdefault(vid, set()).update((snap.get("chain") or {}))
        return {v: sorted(ks) for v, ks in out.items()}

    def state_names(self, vertex: str, op_key: str) -> list[str]:
        names: set = set()
        for snap in self._op_snapshots(vertex, op_key):
            keyed = snap.get("keyed") or {}
            inner = keyed.get("backend", keyed)
            if inner.get("kind") in ("changelog", "changelog-dstl"):
                # states created after the last materialization exist only
                # in the log — union those names in. Inline format carries
                # the log/mat; DSTL carries handles, so restore a scratch
                # backend and take its table names
                if inner.get("kind") == "changelog":
                    names.update(rec[1] for rec in inner.get("log", ()))
                    inner = inner.get("mat") or {}
                else:
                    # handles alone give the names: base pickle's table
                    # keys + each log record's state-name slot — no full
                    # restore just to list names
                    import pickle as _pk

                    from ..state.dstl import read_any_base, read_any_segment
                    if inner.get("base") is not None:
                        base = _pk.loads(read_any_base(
                            inner["driver"], inner["base"],
                            self.changelog_root))
                        names.update(base.get("states", {}))
                    for h in inner.get("segments", []):
                        names.update(rec[1] for _seq, rec
                                     in read_any_segment(
                                         h, self.changelog_root))
                    inner = {}
            names.update(inner.get("states", {}))
        return sorted(names)

    def _op_snapshots(self, vertex: str, op_key: str) -> Iterator[dict]:
        for tid, snap in self.checkpoint.task_snapshots.items():
            if tid.rsplit("#", 1)[0] != vertex:
                continue
            op = (snap.get("chain") or {}).get(op_key)
            if op:
                yield op

    # -- reads -------------------------------------------------------------
    def keyed_state(self, vertex: str, op_key: str,
                    state_name: str) -> list[KeyedStateRecord]:
        """All (key, namespace, value) entries of one state across
        subtasks (reference readKeyedState)."""
        out: list[KeyedStateRecord] = []
        for op in self._op_snapshots(vertex, op_key):
            if op.get("keyed"):
                out.extend(_iter_heap_states(op["keyed"], state_name,
                                             self.changelog_root))
        return out

    def operator_state(self, vertex: str, op_key: str,
                       list_name: str) -> list:
        """Union of one operator-list state across subtasks
        (reference readListState)."""
        out: list = []
        for op in self._op_snapshots(vertex, op_key):
            lists = (op.get("operator") or {}).get("lists", {})
            out.extend(lists.get(list_name, []))
        return out

    def reader_state(self, vertex: str) -> dict[int, Any]:
        """Source reader positions per subtask."""
        out: dict[int, Any] = {}
        for tid, snap in self.checkpoint.task_snapshots.items():
            vid, sub = tid.rsplit("#", 1)
            if vid == vertex and snap.get("reader") is not None:
                out[int(sub)] = snap["reader"]
        return out


class SavepointWriter:
    """Create or transform savepoints offline (reference SavepointWriter:
    from_existing + bootstrap/patch/remove, then write)."""

    def __init__(self, base: Optional[CompletedCheckpoint] = None,
                 max_parallelism: int = 128):
        self.max_parallelism = max_parallelism
        self._snapshots: dict[str, dict] = (
            {tid: _deep_copy(snap)
             for tid, snap in base.task_snapshots.items()}
            if base is not None else {})
        self._vertex_parallelism: dict[str, int] = (
            dict(base.vertex_parallelism) if base is not None else {})
        self._vertex_uids: dict[str, str] = (
            dict(base.vertex_uids) if base is not None else {})

    @staticmethod
    def from_existing(path: str) -> "SavepointWriter":
        return SavepointWriter(SavepointReader.read(path).checkpoint)

    # -- transforms --------------------------------------------------------
    def remove_operator(self, vertex: str, op_key: str) -> "SavepointWriter":
        for tid, snap in self._snapshots.items():
            if tid.rsplit("#", 1)[0] == vertex:
                (snap.get("chain") or {}).pop(op_key, None)
        return self

    def with_keyed_state(self, vertex: str, op_key: str, state_name: str,
                         records: Iterable, parallelism: int = 1,
                         ) -> "SavepointWriter":
        """Bootstrap/overwrite one keyed state from (key, value) or
        (key, namespace, value) records, laid out per key group exactly as
        the heap backend snapshots it."""
        per_sub_states: list[dict] = [
            {} for _ in range(parallelism)]
        from ..core.keygroups import operator_index_for_key_group
        for rec in records:
            if len(rec) == 2:
                key, value = rec
                ns = None
            else:
                key, ns, value = rec
            kg = assign_to_key_group(key, self.max_parallelism)
            sub = operator_index_for_key_group(self.max_parallelism,
                                               parallelism, kg)
            per_kg = per_sub_states[sub].setdefault(kg, [])
            per_kg.append(((key, ns), value, None))

        self._vertex_parallelism[vertex] = parallelism
        # drop stale subtasks beyond the new parallelism: restore unions
        # keyed state across ALL task snapshots, so leftovers would
        # resurrect pre-bootstrap values
        for tid in list(self._snapshots):
            vid, sub = tid.rsplit("#", 1)
            if vid == vertex and int(sub) >= parallelism:
                del self._snapshots[tid]
        for sub in range(parallelism):
            tid = f"{vertex}#{sub}"
            snap = self._snapshots.setdefault(tid, {})
            chain = snap.setdefault("chain", {})
            op = chain.setdefault(op_key, {})
            keyed = op.setdefault("keyed", {"backend": {"kind": "heap",
                                                        "states": {}},
                                            "timers": {}})
            keyed.setdefault("timers", {})  # keyed operators expect the key
            inner = keyed.setdefault("backend", {"kind": "heap",
                                                 "states": {}})
            inner.setdefault("states", {})[state_name] = per_sub_states[sub]
        return self

    def transform_keyed_state(self, vertex: str, op_key: str,
                              state_name: str,
                              fn: Callable[[Any, Any, Any], Optional[Any]]
                              ) -> "SavepointWriter":
        """Apply fn(key, namespace, value) -> new value (None deletes) to
        every entry of one state in place."""
        for tid, snap in self._snapshots.items():
            if tid.rsplit("#", 1)[0] != vertex:
                continue
            op = (snap.get("chain") or {}).get(op_key) or {}
            keyed = op.get("keyed") or {}
            inner = keyed.get("backend", keyed)
            if inner.get("kind") in ("changelog", "changelog-dstl"):
                raise NotImplementedError(
                    "transforming changelog-backend state requires "
                    "materialization first (read + with_keyed_state)")
            per_kg = inner.get("states", {}).get(state_name)
            if not per_kg:
                continue
            for kg, items in per_kg.items():
                new_items = []
                for kn, value, expiry in items:
                    key, ns = tuple(kn) if isinstance(kn, list) else kn
                    nv = fn(key, ns, value)
                    if nv is not None:
                        new_items.append(((key, ns), nv, expiry))
                per_kg[kg] = new_items
        return self

    # -- output ------------------------------------------------------------
    def with_uid(self, vertex: str, uid: str) -> "SavepointWriter":
        """Stable operator uid for restore into resubmitted programs."""
        self._vertex_uids[vertex] = uid
        return self

    def write(self, directory: str,
              savepoint_id: int = 1) -> CompletedCheckpoint:
        cp = CompletedCheckpoint(
            checkpoint_id=savepoint_id, timestamp=time.time(),
            task_snapshots=self._snapshots, is_savepoint=True,
            vertex_parallelism=dict(self._vertex_parallelism),
            vertex_uids=dict(self._vertex_uids))
        return FsCheckpointStorage(directory).store(cp)


def _deep_copy(obj):
    import copy
    return copy.deepcopy(obj)
