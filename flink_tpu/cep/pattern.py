"""CEP Pattern API.

Analog of the reference's fluent pattern DSL (flink-cep
pattern/Pattern.java: begin:137, where:164, or:184, until:228, within:254,
next:283, notNext:294, followedBy:312, notFollowedBy:325, followedByAny:343,
optional:353, oneOrMore:371, times:418, greedy:404, consecutive:559,
allowCombinations:519; Quantifier.java). Conditions are predicates over the
event as a dict ``{column: value}``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["Pattern", "MalformedPatternError"]

# contiguity between stages / inside loops (reference Quantifier
# ConsumingStrategy: STRICT, SKIP_TILL_NEXT, SKIP_TILL_ANY)
STRICT = "strict"
RELAXED = "relaxed"          # followedBy / skip till next
NDR = "ndr"                  # followedByAny / skip till any

Predicate = Callable[[dict], bool]


class MalformedPatternError(ValueError):
    pass


@dataclass
class Stage:
    """One compiled pattern node."""

    name: str
    contiguity: str = RELAXED        # vs the previous stage
    preds: list = field(default_factory=list)       # OR-combined
    # context predicates: p(event, by_name) where by_name maps pattern
    # names to the events captured SO FAR in this partial (reference
    # IterativeCondition.Context.getEventsForPattern — what SQL
    # MATCH_RECOGNIZE DEFINE clauses compile to)
    ctx_preds: list = field(default_factory=list)
    until: Optional[Predicate] = None
    min_count: int = 1
    max_count: Optional[int] = 1     # None = unbounded
    optional: bool = False
    negated: bool = False            # notNext / notFollowedBy
    greedy: bool = False
    inner_contiguity: str = RELAXED  # within a loop (consecutive -> strict)

    def matches(self, event: dict, ctx: Optional[Callable] = None) -> bool:
        """``ctx`` lazily materializes {pattern name: [event dict, ...]}
        for context predicates; omitted where no history exists (fresh
        start state)."""
        if not self.preds and not self.ctx_preds:
            return True
        if any(p(event) for p in self.preds):
            return True
        if self.ctx_preds:
            by_name = ctx() if ctx is not None else {}
            return any(p(event, by_name) for p in self.ctx_preds)
        return False

    @property
    def looping(self) -> bool:
        return self.max_count is None or self.max_count > 1


class Pattern:
    """Fluent builder over a list of stages; terminal ops live on
    PatternStream (cep/__init__.py)."""

    def __init__(self, stages: list, within_ms: Optional[int] = None):
        self._stages = stages
        self.within_ms = within_ms

    # -- construction ------------------------------------------------------
    @staticmethod
    def begin(name: str) -> "Pattern":
        return Pattern([Stage(name, contiguity=RELAXED)])

    def _last(self) -> Stage:
        return self._stages[-1]

    def _append(self, name: str, contiguity: str,
                negated: bool = False) -> "Pattern":
        if any(s.name == name for s in self._stages):
            raise MalformedPatternError(f"duplicate pattern name {name!r}")
        self._stages.append(Stage(name, contiguity=contiguity,
                                  negated=negated))
        return self

    def next(self, name: str) -> "Pattern":
        return self._append(name, STRICT)

    def followed_by(self, name: str) -> "Pattern":
        return self._append(name, RELAXED)

    def followed_by_any(self, name: str) -> "Pattern":
        return self._append(name, NDR)

    def not_next(self, name: str) -> "Pattern":
        return self._append(name, STRICT, negated=True)

    def not_followed_by(self, name: str) -> "Pattern":
        return self._append(name, RELAXED, negated=True)

    # -- conditions --------------------------------------------------------
    def where(self, pred: Predicate) -> "Pattern":
        self._last().preds.append(pred)
        return self

    def or_(self, pred: Predicate) -> "Pattern":
        return self.where(pred)

    def where_with_history(self, pred: Callable[[dict, dict], bool]
                           ) -> "Pattern":
        """Condition over (event, {name: [captured event dicts]}) — the
        reference's IterativeCondition; SQL MATCH_RECOGNIZE DEFINE clauses
        referencing other pattern variables lower to this."""
        self._last().ctx_preds.append(pred)
        return self

    def until(self, pred: Predicate) -> "Pattern":
        if not self._last().looping:
            raise MalformedPatternError("until() needs a looping stage")
        self._last().until = pred
        return self

    # -- quantifiers -------------------------------------------------------
    def times(self, n: int, to: Optional[int] = None) -> "Pattern":
        s = self._last()
        s.min_count = n
        s.max_count = n if to is None else to
        return self

    def times_or_more(self, n: int) -> "Pattern":
        s = self._last()
        s.min_count = n
        s.max_count = None
        return self

    def one_or_more(self) -> "Pattern":
        return self.times_or_more(1)

    def optional(self) -> "Pattern":
        self._last().optional = True
        return self

    def greedy(self) -> "Pattern":
        self._last().greedy = True
        return self

    def consecutive(self) -> "Pattern":
        """Strict contiguity inside a loop (reference consecutive())."""
        self._last().inner_contiguity = STRICT
        return self

    def allow_combinations(self) -> "Pattern":
        self._last().inner_contiguity = NDR
        return self

    def within(self, ms: int) -> "Pattern":
        self.within_ms = int(ms)
        return self

    # -- compile -----------------------------------------------------------
    def compile(self) -> list:
        """Validate and return the stage list for the NFA."""
        if not self._stages:
            raise MalformedPatternError("empty pattern")
        if self._stages[0].negated:
            raise MalformedPatternError("pattern cannot start with NOT")
        if self._stages[-1].negated and self.within_ms is None:
            raise MalformedPatternError(
                "notFollowedBy cannot be the last pattern without within()")
        for s in self._stages:
            if s.negated and (s.looping or s.optional):
                raise MalformedPatternError(
                    "NOT patterns cannot be looping or optional")
        if all(s.negated or s.optional for s in self._stages):
            raise MalformedPatternError("pattern needs a positive stage")
        return list(self._stages)
