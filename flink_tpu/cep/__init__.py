"""CEP: complex event processing over keyed streams.

Analog of the reference's flink-cep library (CEP.java, PatternStream.java:
``CEP.pattern(stream, pattern).select(fn)``). Patterns compile to an NFA
(nfa.py) driven by the CepOperator per key in event-time order.

Usage::

    pat = (Pattern.begin("start").where(lambda e: e["v"] == 1)
           .followed_by("end").where(lambda e: e["v"] == 2)
           .within(10_000))
    out = CEP.pattern(ds, pat, key="user") \
             .select(lambda m: (m["start"][0]["user"],), out_schema)
"""

from __future__ import annotations

from typing import Callable, Optional

from ..core.records import Schema
from .nfa import NFA, Match, NO_SKIP, SKIP_PAST_LAST_EVENT
from .operator import CepOperator
from .pattern import MalformedPatternError, Pattern

__all__ = ["CEP", "Pattern", "PatternStream", "Match", "NFA",
           "MalformedPatternError", "NO_SKIP", "SKIP_PAST_LAST_EVENT",
           "CepOperator"]


class PatternStream:
    def __init__(self, stream, pattern: Pattern, key: str,
                 skip_strategy: str = NO_SKIP,
                 greedy_per_start: bool = False,
                 order_column: str = None):
        self.stream = stream
        self.pattern = pattern
        self.key = key
        self.skip_strategy = skip_strategy
        self.greedy_per_start = greedy_per_start
        self.order_column = order_column

    def with_skip_strategy(self, strategy: str) -> "PatternStream":
        return PatternStream(self.stream, self.pattern, self.key, strategy,
                             self.greedy_per_start, self.order_column)

    def _build(self, select_fn, out_schema: Schema, flat: bool):
        stages = self.pattern.compile()
        within = self.pattern.within_ms
        key = self.key
        skip = self.skip_strategy
        greedy = self.greedy_per_start
        order_col = self.order_column
        keyed = self.stream.key_by(key)

        def factory():
            return CepOperator(
                NFA(stages, within, skip, greedy_per_start=greedy), key,
                select_fn, out_schema, flat_select=flat,
                order_column=order_col)

        out = keyed._one_input("CepOperator", factory,
                               key_extractor=keyed.key_extractor)
        out._sql_schema = out_schema
        return out

    def select(self, fn: Callable[[Match], tuple], out_schema: Schema):
        """One output row per match (reference PatternSelectFunction)."""
        return self._build(fn, out_schema, flat=False)

    def flat_select(self, fn, out_schema: Schema):
        """Zero or more output rows per match (PatternFlatSelectFunction)."""
        return self._build(fn, out_schema, flat=True)


class CEP:
    @staticmethod
    def pattern(stream, pattern: Pattern, key: str) -> PatternStream:
        return PatternStream(stream, pattern, key)
