"""NFA over one key's event sequence.

Analog of the reference's NFA + SharedBuffer machinery (flink-cep
nfa/NFA.java — computeNextStates with TAKE/IGNORE/PROCEED branching,
nfa/aftermatch/AfterMatchSkipStrategy.java), reduced to an explicit
partial-match list: each partial is (stage, count, captured events). The
branching matrix implements the three consuming strategies (STRICT /
SKIP_TILL_NEXT / SKIP_TILL_ANY) between stages and inside loops, greedy
loops, optional stages, NOT-pattern guards, and the within() window.

Host-side by design: conditions are arbitrary Python predicates, and CEP
state is tiny compared to window/agg state. The batch path still amortizes —
the operator buffers a whole micro-batch per key and advances the NFA once
per event without any per-event operator dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional

from .pattern import NDR, RELAXED, STRICT, Stage

__all__ = ["NFA", "Match", "NO_SKIP", "SKIP_PAST_LAST_EVENT"]

NO_SKIP = "no_skip"
SKIP_PAST_LAST_EVENT = "skip_past_last_event"
SKIP_TO_NEXT_ROW = "skip_to_next_row"


@dataclass(frozen=True)
class Event:
    seq: int
    ts: int
    data: dict


@dataclass
class Match:
    """One complete match: pattern name -> list of event dicts."""

    events: dict
    start_ts: int
    end_ts: int
    last_seq: int
    start_seq: int = 0

    def __getitem__(self, name: str) -> list:
        return self.events[name]


@dataclass
class _Partial:
    stage: int                   # index into positive stage order
    count: int                   # events taken in the current stage
    taking: bool                 # loop still accepting (until/consecutive)
    captured: tuple              # ((stage_idx, Event), ...)
    start_ts: int
    start_seq: int
    ignored_since_advance: int   # events ignored since last take/proceed


@dataclass
class _PendingBest:
    """Deferred match candidate for greedy-per-start selection: the best
    (longest) completed match for one start row, held back while a live
    partial with the same start could still grow into a longer one.
    Lives inside the partials list so it checkpoints with keyed state."""

    start_seq: int
    start_ts: int
    match: Match


class NFA:
    def __init__(self, stages: list, within_ms: Optional[int] = None,
                 skip_strategy: str = NO_SKIP,
                 greedy_per_start: bool = False):
        """``greedy_per_start`` defers emission so exactly ONE match — the
        longest — comes out per start row (SQL:2016 MATCH_RECOGNIZE
        quantifier greediness, resolved by deferral instead of
        backtracking). Combine with SKIP_PAST_LAST_EVENT for AFTER MATCH
        SKIP PAST LAST ROW, or SKIP_TO_NEXT_ROW for one-match-per-start
        without overlap pruning."""
        self.stages = stages
        self.within_ms = within_ms
        self.skip = skip_strategy
        self.greedy_per_start = greedy_per_start
        # positive stage indices in order; negatives act as guards between
        self.pos: list[int] = [i for i, s in enumerate(stages)
                               if not s.negated]
        if not self.pos:
            raise ValueError("pattern has no positive stages")

    # -- helpers -----------------------------------------------------------
    def _stage(self, pi: int) -> Stage:
        return self.stages[self.pos[pi]]

    def _guards_between(self, pi: int) -> list[Stage]:
        """Negated stages between positive pi and positive pi+1 (or the
        trailing negatives when pi is the last positive stage)."""
        lo = self.pos[pi]
        hi = (self.pos[pi + 1] if pi + 1 < len(self.pos)
              else len(self.stages))
        return [self.stages[i] for i in range(lo + 1, hi)
                if self.stages[i].negated]

    def _next_candidates(self, pi: int) -> list[int]:
        """Positive stages reachable from pi by PROCEED, skipping optional
        stages (each skipped stage must be optional)."""
        out = []
        j = pi + 1
        while j < len(self.pos):
            out.append(j)
            if not self._stage(j).optional:
                break
            j += 1
        return out

    def _captured_ctx(self, captured: tuple):
        """Lazy, memoized {stage name: [event dicts]} view of a partial's
        captured events, for context predicates (pattern.Stage.ctx_preds).
        matches() can run several times per event per partial (stage,
        guards, next candidates) — build once."""
        cache: list = []

        def build() -> dict:
            if not cache:
                out: dict[str, list] = {}
                for si, ev in captured:
                    out.setdefault(self.stages[si].name, []).append(ev.data)
                cache.append(out)
            return cache[0]
        return build

    def _is_final(self, pi: int, count: int) -> bool:
        if count < self._stage(pi).min_count:
            return False
        # all later positive stages must be optional
        return all(self._stage(j).optional
                   for j in range(pi + 1, len(self.pos)))

    # -- greedy-per-start deferral ----------------------------------------
    @staticmethod
    def _match_rank(m: Match) -> tuple:
        return (m.last_seq, sum(len(v) for v in m.events.values()))

    def _resolve_pending(self, pending: list, raw: list, live: list,
                         flush_all: bool = False) -> tuple[list, list]:
        """Merge newly completed matches into the per-start bests; release
        a best once nothing live could still extend OR PRECEDE it (an
        earlier live start may yet produce a match that skip-past-last
        would prefer). Returns (still_pending, released_matches)."""
        by_start = {pb.start_seq: pb for pb in pending}
        for m in raw:
            cur = by_start.get(m.start_seq)
            if cur is None or self._match_rank(m) > self._match_rank(
                    cur.match):
                by_start[m.start_seq] = _PendingBest(m.start_seq,
                                                     m.start_ts, m)
        live_starts = {p.start_seq for p in live}
        min_live = min(live_starts) if live_starts else None
        released: list[Match] = []
        still: list[_PendingBest] = []
        horizon = -1
        for pb in sorted(by_start.values(), key=lambda x: x.start_seq):
            if pb.start_seq <= horizon:
                continue                      # overlapped a released match
            blocked = (not flush_all
                       and (pb.start_seq in live_starts
                            or (self.skip == SKIP_PAST_LAST_EVENT
                                and min_live is not None
                                and min_live < pb.start_seq)))
            if blocked:
                still.append(pb)
                continue
            released.append(pb.match)
            if self.skip == SKIP_PAST_LAST_EVENT:
                horizon = pb.match.last_seq
        if horizon >= 0:
            live = [p for p in live if p.start_seq > horizon]
            still = [pb for pb in still if pb.start_seq > horizon]
        return still + live, released

    # -- core --------------------------------------------------------------
    def advance(self, partials: list, event: Event
                ) -> tuple[list, list]:
        """One event through all partials + the start state. Returns
        (new partials, matches)."""
        pending: list[_PendingBest] = []
        if self.greedy_per_start:
            pending = [p for p in partials
                       if isinstance(p, _PendingBest)]
            partials = [p for p in partials
                        if not isinstance(p, _PendingBest)]
        out: list[_Partial] = []
        matches: list[Match] = []
        seen_match_keys: set = set()

        def emit(p: _Partial) -> None:
            key = tuple(e.seq for _, e in p.captured)
            if key in seen_match_keys:
                return
            seen_match_keys.add(key)
            ev_map: dict[str, list] = {}
            for si, e in p.captured:
                ev_map.setdefault(self.stages[si].name, []).append(e.data)
            matches.append(Match(ev_map, p.start_ts, event.ts,
                                 max(e.seq for _, e in p.captured),
                                 p.start_seq))

        def offer(p: _Partial) -> None:
            """Register a successor; emit when it reaches a final state."""
            if self._is_final(p.stage, p.count):
                if self._guards_between(p.stage):
                    # trailing NOT pattern: defer to timeout (pruning)
                    out.append(p)
                    return
                emit(p)
                s = self._stage(p.stage)
                if p.taking and s.looping and (
                        s.max_count is None or p.count < s.max_count):
                    out.append(p)  # loop can still extend into longer matches
            else:
                out.append(p)

        # existing partials
        for p in partials:
            if (self.within_ms is not None
                    and event.ts - p.start_ts > self.within_ms):
                self._flush_deferred(p, event.ts, emit_fn=matches)
                continue  # timed out
            out_branches = self._advance_one(p, event, emit_offer=offer)
            out.extend(out_branches)

        # start a new partial at the first positive stage (every event may
        # begin a match — reference NFA start state self-loop)
        first = self._stage(0)
        start_candidates = [0] + ([] if not first.optional
                                  else self._next_candidates(0))
        for pi in start_candidates:
            s = self._stage(pi)
            if not s.negated and s.matches(event.data,
                                           self._captured_ctx(())):
                p = _Partial(pi, 1, True, ((self.pos[pi], event),),
                             event.ts, event.seq, 0)
                offer(p)
                break  # only the first stage that matches starts the run

        if self.greedy_per_start:
            return self._resolve_pending(pending, matches, out)

        if self.skip == SKIP_PAST_LAST_EVENT and matches:
            # keep the earliest-starting match, drop matches and partials
            # overlapping it (reference AfterMatchSkipStrategy)
            matches.sort(key=lambda m: m.start_seq)
            kept: list[Match] = []
            horizon = -1
            for m in matches:
                if m.start_seq > horizon:
                    kept.append(m)
                    horizon = m.last_seq
            matches = kept
            out = [p for p in out if p.start_seq > horizon]
        return out, matches

    def _advance_one(self, p: _Partial, event: Event, emit_offer) -> list:
        """TAKE / PROCEED / IGNORE branching for one partial."""
        s = self._stage(p.stage)
        branches: list[_Partial] = []
        ctx = self._captured_ctx(p.captured)
        e_matches = s.matches(event.data, ctx)

        # until() stops the loop from taking (event not consumed)
        taking = p.taking
        if taking and s.until is not None and p.count >= 1 \
                and s.until(event.data):
            taking = False

        can_take = (taking and e_matches
                    and (s.max_count is None or p.count < s.max_count))
        took = False
        if can_take:
            emit_offer(replace(
                p, count=p.count + 1, taking=taking,
                captured=p.captured + ((self.pos[p.stage], event),),
                ignored_since_advance=0))
            took = True

        # PROCEED to following stage(s) once the current one is satisfied
        proceeded = False
        can_proceed = p.count >= s.min_count and not (s.greedy and can_take)
        if can_proceed:
            guards = self._guards_between(p.stage)
            guard_hit = any(
                g.matches(event.data, ctx)
                and (g.contiguity != STRICT or p.ignored_since_advance == 0)
                for g in guards)
            if guard_hit:
                return branches  # NOT pattern matched: path dies
            for pj in self._next_candidates(p.stage):
                nxt = self._stage(pj)
                # STRICT next stage: the event must IMMEDIATELY follow the
                # last taken event — a partial that ignored anything since
                # its last take cannot strict-proceed (this is what makes
                # keeping the source partial alive after a proceed safe).
                # Only THIS candidate is blocked: a later optional-skip
                # candidate may be RELAXED and still reachable.
                if (nxt.contiguity == STRICT
                        and p.ignored_since_advance > 0):
                    if nxt.optional:
                        continue
                    break  # a required strict stage blocks everything after
                if nxt.matches(event.data, ctx):
                    emit_offer(replace(
                        p, stage=pj, count=1, taking=True,
                        captured=p.captured + ((self.pos[pj], event),),
                        ignored_since_advance=0))
                    proceeded = True
                    if nxt.contiguity != NDR:
                        break

        # IGNORE: keep waiting (contiguity-dependent)
        in_loop = p.count >= 1
        cont = s.inner_contiguity if in_loop else s.contiguity
        ignore_ok = True
        new_taking = taking
        if in_loop:
            if cont == STRICT and taking:
                # consecutive(): ANY ignored event — matching or not —
                # breaks the run; the kept branch may still await the next
                # stage but can never extend the loop again (ignoring a
                # MATCHING event and taking a later one would be
                # allow_combinations semantics)
                new_taking = False
            if cont == RELAXED and took:
                ignore_ok = False
            # waiting for next stage is allowed once min met as long as the
            # loop could still take later events (relaxed inner): a strict
            # next stage is protected by the ignored_since_advance gate on
            # proceed, so a kept partial can never strict-proceed across a
            # gap. With a STRICT inner loop (consecutive / MATCH_RECOGNIZE)
            # a miss ends the loop AND the wait: the next event can neither
            # extend the loop nor strict-follow the last take.
            if p.count >= s.min_count:
                nxts = self._next_candidates(p.stage)
                # only candidates still REACHABLE after this ignore matter:
                # strict candidates die once anything was ignored; a
                # relaxed candidate behind optional strict ones keeps the
                # wait alive (followed_by's skip-till-next semantics)
                all_strict = nxts and all(
                    self._stage(j).contiguity == STRICT for j in nxts)
                if all_strict and not took and (cont == STRICT
                                                or not proceeded):
                    ignore_ok = False
        else:
            if cont == STRICT and not took:
                return branches  # strict start of stage: miss kills path
            if cont == RELAXED and took:
                ignore_ok = False
        if ignore_ok and not (took and cont == RELAXED and not in_loop):
            branches.append(replace(
                p, taking=new_taking,
                ignored_since_advance=p.ignored_since_advance + 1))
        return branches

    def _flush_deferred(self, p: _Partial, now_ts: int, emit_fn) -> None:
        """A timed-out partial whose positive stages are complete and whose
        only remaining obligation was a trailing NOT pattern matches at
        timeout (reference notFollowedBy+within semantics)."""
        if not self._is_final(p.stage, p.count):
            return
        if not self._guards_between(p.stage):
            return
        ev_map: dict[str, list] = {}
        for si, e in p.captured:
            ev_map.setdefault(self.stages[si].name, []).append(e.data)
        end_ts = (p.start_ts + self.within_ms if self.within_ms is not None
                  else now_ts)
        emit_fn.append(Match(ev_map, p.start_ts, end_ts,
                             max(e.seq for _, e in p.captured),
                             p.start_seq))

    END_OF_STREAM_TS = 1 << 61   # watermark at/above this = no more input

    def prune(self, partials: list, watermark_ts: int) -> tuple[list, list]:
        """Drop partials whose within-window has passed; deferred
        trailing-NOT matches fire here. In greedy-per-start mode a prune
        also re-resolves pending bests: timed-out partials can no longer
        extend them, and end-of-stream releases everything."""
        pending: list[_PendingBest] = []
        if self.greedy_per_start:
            pending = [p for p in partials if isinstance(p, _PendingBest)]
            partials = [p for p in partials
                        if not isinstance(p, _PendingBest)]
        end_of_stream = watermark_ts >= self.END_OF_STREAM_TS
        if self.within_ms is None:
            kept, matches = list(partials), []
        else:
            kept, matches = [], []
            for p in partials:
                if watermark_ts - p.start_ts > self.within_ms:
                    self._flush_deferred(p, p.start_ts + self.within_ms,
                                         emit_fn=matches)
                else:
                    kept.append(p)
        if self.greedy_per_start:
            return self._resolve_pending(pending, matches, kept,
                                         flush_all=end_of_stream)
        return kept, matches
