"""CepOperator: keyed NFA pattern matching on a stream.

Analog of the reference's CepOperator (flink-cep
operator/CepOperator.java:82): events are buffered per key and processed in
event-time order when the watermark passes them (the reference's event queue
+ onEventTime), partial matches live in keyed state, matched sequences are
handed to a select function.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

import numpy as np

from ..core.keygroups import assign_to_key_group
from ..core.records import RecordBatch, Schema, scalar as _scalar
from ..runtime.operators.base import OneInputOperator
from .nfa import NFA, Event, Match

__all__ = ["CepOperator"]


class CepOperator(OneInputOperator):
    """``select_fn(match: Match) -> row tuple`` (or an iterable of rows via
    flat_select=True) produces the output; rows follow ``out_schema``."""

    def __init__(self, nfa: NFA, key_column: str,
                 select_fn: Callable[[Match], Any], out_schema: Schema,
                 flat_select: bool = False, name: str = "Cep",
                 order_column: str = None):
        """``order_column`` sorts each watermark-fired buffer by that
        column instead of event time (SQL MATCH_RECOGNIZE ORDER BY over a
        non-time attribute); event-time firing is unchanged."""
        super().__init__(name)
        self.nfa = nfa
        self.key_column = key_column
        self.select_fn = select_fn
        self.out_schema = out_schema
        self.flat_select = flat_select
        self.order_column = order_column
        self._seq = itertools.count()
        # kg -> key -> {"buffer": [Event], "partials": [_Partial]}
        self._state: dict[int, dict[Any, dict]] = {}
        self._late_dropped = 0

    def _key_state(self, key) -> dict:
        kg = assign_to_key_group(key, self.ctx.max_parallelism)
        return (self._state.setdefault(kg, {})
                .setdefault(key, {"buffer": [], "partials": []}))

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        names = [f.name for f in batch.schema.fields]
        cols = [batch.column(n) for n in names]
        keys = batch.column(self.key_column)
        ts_arr = batch.timestamps
        # late events (behind the watermark their key already fired past)
        # quarantine to the dead-letter side output, like the window
        # operators' late_dropped path — never silently vanish
        late = np.asarray(ts_arr) <= self.current_watermark
        if late.any():
            from ..metrics import DEVICE_STATS
            n_late = int(late.sum())
            self._late_dropped += n_late
            DEVICE_STATS.note_dead_letter(n_late)
            try:
                self.output.emit_side("dead-letter", batch.filter(late))
            except NotImplementedError:
                pass  # no dead-letter consumer wired: counted, then dropped
        for i in range(batch.n):
            if late[i]:
                continue
            data = {n: _scalar(c[i]) for n, c in zip(names, cols)}
            ev = Event(next(self._seq), int(ts_arr[i]), data)
            self._key_state(_scalar(keys[i]))["buffer"].append(ev)

    @property
    def late_dropped(self) -> int:
        return self._late_dropped

    def process_watermark(self, watermark) -> None:
        self._fire_up_to(watermark.timestamp)
        super().process_watermark(watermark)

    def finish(self) -> None:
        self._fire_up_to((1 << 62))

    def _fire_up_to(self, wm_ts: int) -> None:
        out_rows, out_ts = [], []
        for kg_map in self._state.values():
            for key in list(kg_map):
                st = kg_map[key]
                ready = [e for e in st["buffer"] if e.ts <= wm_ts]
                if not ready and not st["partials"]:
                    if not st["buffer"]:
                        del kg_map[key]  # fully drained: free the key
                    continue
                st["buffer"] = [e for e in st["buffer"] if e.ts > wm_ts]
                if self.order_column is not None:
                    # the declared ordering must BE the time attribute:
                    # watermark firing only orders rows within one fire, so
                    # any other column silently mis-orders across fires —
                    # the reference restricts MATCH_RECOGNIZE ORDER BY to
                    # the time attribute for the same reason. Loud > wrong.
                    for e in ready:
                        if e.data.get(self.order_column) != e.ts:
                            raise ValueError(
                                f"ORDER BY {self.order_column!r} is not the "
                                "stream's time attribute (row value "
                                f"{e.data.get(self.order_column)!r} != "
                                f"event time {e.ts}); MATCH_RECOGNIZE "
                                "requires ordering by the time attribute")
                ready.sort(key=lambda e: (e.ts, e.seq))
                partials = st["partials"]
                for ev in ready:
                    partials, matches = self.nfa.advance(partials, ev)
                    self._collect(matches, ev.ts, out_rows, out_ts)
                partials, timed_out = self.nfa.prune(partials, wm_ts)
                self._collect(timed_out, wm_ts, out_rows, out_ts)
                st["partials"] = partials
                if not partials and not st["buffer"]:
                    del kg_map[key]
        if out_rows:
            self.output.emit(RecordBatch.from_rows(
                self.out_schema, out_rows, out_ts))

    def _collect(self, matches: list, ts: int, out_rows: list,
                 out_ts: list) -> None:
        for m in matches:
            if self.flat_select:
                for row in self.select_fn(m):
                    out_rows.append(tuple(row))
                    out_ts.append(m.end_ts)
            else:
                out_rows.append(tuple(self.select_fn(m)))
                out_ts.append(m.end_ts)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": {"cep": {
            kg: {k: {"buffer": list(st["buffer"]),
                     "partials": list(st["partials"])}
                 for k, st in m.items()}
            for kg, m in self._state.items()}}},
            "operator": {"seq": next(self._seq)}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        for snap in keyed_snapshots:
            for kg, entries in snap.get("backend", {}).get("cep", {}).items():
                if kg in self.ctx.key_group_range:
                    tgt = self._state.setdefault(kg, {})
                    for k, st in entries.items():
                        cur = tgt.setdefault(k,
                                             {"buffer": [], "partials": []})
                        cur["buffer"].extend(st["buffer"])
                        cur["partials"].extend(st["partials"])
        if operator_snapshot and "seq" in operator_snapshot:
            self._seq = itertools.count(operator_snapshot["seq"])

