"""Native host-runtime library: build, load, and ctypes bindings.

See native.cpp for what lives here and why (the reference's FRocksDB /
lz4-JNI / Unsafe analog layer). The .so is compiled on first import with
g++ -O3 (cached next to the source, rebuilt when the source is newer) and
loaded via ctypes; every function has a numpy/zlib fallback so the package
works without a toolchain.

Public surface:
    NATIVE_AVAILABLE          -- True when the C++ library loaded
    murmur_mix_batch(codes)   -- int32 murmur of uint32 codes
    key_group_batch(codes, max_parallelism)
    compress(data) / decompress(data)  -- block codec (native LZ4-style or
                                          zlib fallback; self-describing tag)
    HostHashIndex             -- int64 -> dense slot index (native or dict)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import Optional

import numpy as np

__all__ = [
    "NATIVE_AVAILABLE", "murmur_mix_batch", "key_group_batch",
    "compress", "decompress", "HostHashIndex",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native.cpp")
_SO = os.path.join(_HERE, "_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build() -> bool:
    try:
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
               "-o", _SO, _SRC]
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            # -march=native can be unsupported in sandboxes; retry plain
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC]
            r = subprocess.run(cmd, capture_output=True, timeout=120)
        return r.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                if not _build():
                    return None
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        # signatures
        i64, u8p, u32p, i32p, i64p = (
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64))
        lib.murmur_mix_batch.argtypes = [u32p, i64, i32p]
        lib.key_group_batch.argtypes = [u32p, i64, ctypes.c_int32, i32p]
        lib.block_compress_bound.argtypes = [i64]
        lib.block_compress_bound.restype = i64
        lib.block_compress.argtypes = [u8p, i64, u8p]
        lib.block_compress.restype = i64
        lib.block_decompress.argtypes = [u8p, i64, u8p, i64]
        lib.block_decompress.restype = i64
        lib.block_raw_len.argtypes = [u8p, i64]
        lib.block_raw_len.restype = i64
        lib.hi_create.argtypes = [i64]
        lib.hi_create.restype = ctypes.c_void_p
        lib.hi_free.argtypes = [ctypes.c_void_p]
        lib.hi_size.argtypes = [ctypes.c_void_p]
        lib.hi_size.restype = i64
        lib.hi_upsert_batch.argtypes = [ctypes.c_void_p, i64p, i64, i32p]
        lib.hi_lookup_batch.argtypes = [ctypes.c_void_p, i64p, i64, i32p]
        _lib = lib
        return _lib


_loaded = _load()
NATIVE_AVAILABLE = _loaded is not None


def _u32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _u8p(b):
    return ctypes.cast(ctypes.c_char_p(bytes(b) if not isinstance(b, bytes)
                                       else b),
                       ctypes.POINTER(ctypes.c_uint8))


def murmur_mix_batch(codes: np.ndarray) -> np.ndarray:
    """Vectorized reference murmur (bit-exact with keygroups.murmur_mix)."""
    codes = np.ascontiguousarray(codes, dtype=np.uint32)
    if _loaded is not None:
        out = np.empty(len(codes), np.int32)
        _loaded.murmur_mix_batch(_u32p(codes), len(codes), _i32p(out))
        return out
    from ..core.keygroups import murmur_mix
    return murmur_mix(codes)


def key_group_batch(codes: np.ndarray, max_parallelism: int) -> np.ndarray:
    codes = np.ascontiguousarray(codes, dtype=np.uint32)
    if _loaded is not None:
        out = np.empty(len(codes), np.int32)
        _loaded.key_group_batch(_u32p(codes), len(codes),
                                np.int32(max_parallelism), _i32p(out))
        return out
    from ..core.keygroups import murmur_mix
    return (murmur_mix(codes) % max_parallelism).astype(np.int32)


# -- block codec ------------------------------------------------------------
# 1-byte tag so either side can decode frames from the other implementation
_TAG_NATIVE = b"\x01"
_TAG_ZLIB = b"\x02"


def compress(data: bytes) -> bytes:
    if _loaded is not None:
        n = len(data)
        bound = _loaded.block_compress_bound(n)
        out = np.empty(bound, np.uint8)
        written = _loaded.block_compress(
            _u8p(data), n, out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)))
        return _TAG_NATIVE + out[:written].tobytes()
    return _TAG_ZLIB + zlib.compress(data, 1)


def decompress(data: bytes) -> bytes:
    tag, payload = data[:1], data[1:]
    if tag == _TAG_ZLIB:
        return zlib.decompress(payload)
    if tag != _TAG_NATIVE:
        raise ValueError("unknown compression tag")
    if _loaded is None:
        # durable data must stay recoverable on hosts without a toolchain:
        # slow pure-Python decoder for the native frame format
        return _py_block_decompress(payload)
    raw = _loaded.block_raw_len(_u8p(payload), len(payload))
    if raw < 0:
        raise ValueError("corrupt compressed block")
    out = np.empty(max(raw, 1), np.uint8)
    got = _loaded.block_decompress(
        _u8p(payload), len(payload),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), raw)
    if got != raw:
        raise ValueError("corrupt compressed block")
    return out[:raw].tobytes()


def _py_block_decompress(src: bytes) -> bytes:
    """Pure-Python decoder for native.cpp's block format (see the frame
    spec there); correctness fallback only — native path is ~100x faster."""
    if len(src) < 8:
        raise ValueError("corrupt compressed block")
    raw = int.from_bytes(src[:8], "little", signed=True)
    if raw < 0:
        raise ValueError("corrupt compressed block")
    ip, iend = 8, len(src)
    out = bytearray()
    while len(out) < raw:
        if ip >= iend:
            raise ValueError("corrupt compressed block")
        tok = src[ip]
        ip += 1
        lit_len = tok >> 4
        if lit_len == 15:
            while True:
                if ip >= iend:
                    raise ValueError("corrupt compressed block")
                b = src[ip]
                ip += 1
                lit_len += b
                if b != 255:
                    break
        if ip + lit_len > iend or len(out) + lit_len > raw:
            raise ValueError("corrupt compressed block")
        out += src[ip:ip + lit_len]
        ip += lit_len
        if len(out) >= raw:
            break
        if ip + 2 > iend:
            raise ValueError("corrupt compressed block")
        off = int.from_bytes(src[ip:ip + 2], "little")
        ip += 2
        match_len = tok & 15
        if match_len == 15:
            while True:
                if ip >= iend:
                    raise ValueError("corrupt compressed block")
                b = src[ip]
                ip += 1
                match_len += b
                if b != 255:
                    break
        match_len += 4
        if off == 0 or off > len(out) or len(out) + match_len > raw:
            raise ValueError("corrupt compressed block")
        start = len(out) - off
        for i in range(match_len):   # overlap-safe forward copy
            out.append(out[start + i])
    return bytes(out)


class HostHashIndex:
    """int64 key -> dense slot index (insertion order). Native open
    addressing when available, dict fallback otherwise. The host-side twin
    of ops/hash_table.py's device table."""

    def __init__(self, capacity: int = 1024):
        self._native = None
        if _loaded is not None:
            self._native = _loaded.hi_create(int(capacity))
        else:
            self._dict: dict[int, int] = {}

    def upsert(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        out = np.empty(len(keys), np.int32)
        if self._native is not None:
            _loaded.hi_upsert_batch(self._native, _i64p(keys), len(keys),
                                    _i32p(out))
            return out
        d = self._dict
        for i, k in enumerate(keys):
            out[i] = d.setdefault(int(k), len(d))
        return out

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        out = np.empty(len(keys), np.int32)
        if self._native is not None:
            _loaded.hi_lookup_batch(self._native, _i64p(keys), len(keys),
                                    _i32p(out))
            return out
        d = self._dict
        for i, k in enumerate(keys):
            out[i] = d.get(int(k), -1)
        return out

    def __len__(self) -> int:
        if self._native is not None:
            return int(_loaded.hi_size(self._native))
        return len(self._dict)

    def __del__(self):
        native = getattr(self, "_native", None)
        if native is not None and _loaded is not None:
            _loaded.hi_free(native)
            self._native = None
