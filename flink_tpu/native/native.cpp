// Native host-runtime hot paths for flink-tpu.
//
// The reference ships native code where the JVM is too slow or indirect:
// FRocksDB (C++ LSM state store behind RocksDBKeyedStateBackend.java:114),
// lz4-java/zstd JNI block compression (io/compression/
// BlockCompressionFactory.java:68), Netty's native epoll transport, and
// Unsafe-backed MemorySegments (core/memory/MemorySegment.java:70). This
// library is the TPU framework's equivalent layer for the HOST side of the
// runtime (the device side is XLA/Pallas):
//
//   * murmur_mix_batch / key_group_batch — vectorized key-group routing
//     (KeyGroupRangeAssignment.computeKeyGroupForKeyHash) for the exchange
//     hot path; bit-exact with core/keygroups.murmur_mix.
//   * block_compress / block_decompress — an LZ4-style byte-oriented block
//     codec (greedy hash-table matcher, literal/match token stream) used
//     for checkpoint snapshots and DCN spill framing. Self-describing
//     frame, NOT interoperable with upstream LZ4 (deliberate: no external
//     deps), ~lz4-class speed.
//   * hash index — open-addressing int64 -> slot table (linear probing,
//     power-of-two capacity) assigning dense slots in insertion order; the
//     host-side key->row index of the state backends' spill tier (the
//     RocksDB-replacement risk item in SURVEY.md §7).
//
// Built by flink_tpu/native/build.py with g++ -O3; loaded via ctypes
// (no pybind11 in the image). Every entry point has a numpy fallback in
// flink_tpu/native/__init__.py, so the Python package works without a
// toolchain; the native path is an acceleration, not a requirement.

#include <cstdint>
#include <cstring>
#include <cstdlib>

extern "C" {

// ---------------------------------------------------------------------------
// murmur key-group routing
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int r) {
    return (x << r) | (x >> (32 - r));
}

static inline int32_t murmur_mix_one(uint32_t k) {
    const uint32_t C1 = 0xCC9E2D51u, C2 = 0x1B873593u;
    k *= C1;
    k = rotl32(k, 15);
    k *= C2;
    uint32_t h = rotl32(k, 13);
    h = h * 5u + 0xE6546B64u;
    h ^= 4u;  // len(bytes) == 4
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    int32_t s = (int32_t)h;
    if (s == INT32_MIN) return 0;      // reference abs() semantics
    return s < 0 ? -s : s;
}

void murmur_mix_batch(const uint32_t* codes, int64_t n, int32_t* out) {
    for (int64_t i = 0; i < n; ++i) out[i] = murmur_mix_one(codes[i]);
}

void key_group_batch(const uint32_t* codes, int64_t n, int32_t max_par,
                     int32_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = murmur_mix_one(codes[i]) % max_par;
}

// ---------------------------------------------------------------------------
// LZ4-style block codec
//
// Frame: [u64 raw_len][sequence*]
// Sequence: token byte = (lit_len_nibble << 4) | match_len_nibble
//   lit_len_nibble == 15  -> extended length bytes follow (255-run coding)
//   literals follow
//   if any input remains: [u16 little-endian offset][match extension if
//   match_len_nibble == 15]; match length is stored minus MIN_MATCH (4).
//   A block ends when raw_len bytes have been produced.
// ---------------------------------------------------------------------------

static const int MIN_MATCH = 4;
static const int HASH_LOG = 14;

static inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

static inline uint32_t seq_hash(uint32_t v) {
    return (v * 2654435761u) >> (32 - HASH_LOG);
}

static inline uint8_t* write_len(uint8_t* op, uint64_t len) {
    while (len >= 255) { *op++ = 255; len -= 255; }
    *op++ = (uint8_t)len;
    return op;
}

// worst case: raw_len + raw_len/255 + 16 (header + final token)
int64_t block_compress_bound(int64_t raw_len) {
    return raw_len + raw_len / 255 + 32;
}

int64_t block_compress(const uint8_t* src, int64_t n, uint8_t* dst) {
    uint8_t* op = dst;
    std::memcpy(op, &n, 8);
    op += 8;
    if (n == 0) return op - dst;

    int32_t table[1 << HASH_LOG];
    for (int i = 0; i < (1 << HASH_LOG); ++i) table[i] = -1;

    const uint8_t* anchor = src;       // start of pending literals
    const uint8_t* ip = src;
    const uint8_t* iend = src + n;
    const uint8_t* mlimit = iend - MIN_MATCH;  // last position matchable

    while (ip <= mlimit) {
        uint32_t h = seq_hash(read32(ip));
        int64_t cand = table[h];
        table[h] = (int32_t)(ip - src);
        if (cand >= 0 && (ip - src) - cand <= 65535 &&
            read32(src + cand) == read32(ip)) {
            // extend the match
            const uint8_t* match = src + cand;
            const uint8_t* mi = ip + MIN_MATCH;
            const uint8_t* mm = match + MIN_MATCH;
            while (mi < iend && *mi == *mm) { ++mi; ++mm; }
            uint64_t match_len = (uint64_t)(mi - ip);
            uint64_t lit_len = (uint64_t)(ip - anchor);

            uint8_t tok_lit = lit_len >= 15 ? 15 : (uint8_t)lit_len;
            uint64_t mstore = match_len - MIN_MATCH;
            uint8_t tok_match = mstore >= 15 ? 15 : (uint8_t)mstore;
            *op++ = (uint8_t)((tok_lit << 4) | tok_match);
            if (tok_lit == 15) op = write_len(op, lit_len - 15);
            std::memcpy(op, anchor, lit_len);
            op += lit_len;
            uint16_t off = (uint16_t)((ip - src) - cand);
            std::memcpy(op, &off, 2);
            op += 2;
            if (tok_match == 15) op = write_len(op, mstore - 15);
            ip = mi;
            anchor = ip;
        } else {
            ++ip;
        }
    }
    // trailing literals, token with match nibble unused (no offset follows
    // because decompression stops at raw_len)
    uint64_t lit_len = (uint64_t)(iend - anchor);
    uint8_t tok_lit = lit_len >= 15 ? 15 : (uint8_t)lit_len;
    *op++ = (uint8_t)(tok_lit << 4);
    if (tok_lit == 15) op = write_len(op, lit_len - 15);
    std::memcpy(op, anchor, lit_len);
    op += lit_len;
    return op - dst;
}

// returns raw length, or -1 on corrupt input
int64_t block_raw_len(const uint8_t* src, int64_t n) {
    if (n < 8) return -1;
    int64_t raw;
    std::memcpy(&raw, src, 8);
    return raw;
}

int64_t block_decompress(const uint8_t* src, int64_t n, uint8_t* dst,
                         int64_t dst_cap) {
    int64_t raw = block_raw_len(src, n);
    if (raw < 0 || raw > dst_cap) return -1;
    const uint8_t* ip = src + 8;
    const uint8_t* iend = src + n;
    uint8_t* op = dst;
    uint8_t* oend = dst + raw;

    while (op < oend) {
        if (ip >= iend) return -1;
        uint8_t tok = *ip++;
        uint64_t lit_len = tok >> 4;
        if (lit_len == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                lit_len += b;
            } while (b == 255);
        }
        if (ip + lit_len > iend || op + lit_len > oend) return -1;
        std::memcpy(op, ip, lit_len);
        ip += lit_len;
        op += lit_len;
        if (op >= oend) break;  // trailing-literal sequence
        if (ip + 2 > iend) return -1;
        uint16_t off;
        std::memcpy(&off, ip, 2);
        ip += 2;
        uint64_t match_len = (tok & 15);
        if (match_len == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                match_len += b;
            } while (b == 255);
        }
        match_len += MIN_MATCH;
        if (off == 0 || op - dst < off || op + match_len > oend) return -1;
        const uint8_t* match = op - off;
        // overlapping copy must run forward byte-by-byte
        for (uint64_t i = 0; i < match_len; ++i) op[i] = match[i];
        op += match_len;
    }
    return op - dst;
}

// ---------------------------------------------------------------------------
// open-addressing int64 -> dense slot hash index
// ---------------------------------------------------------------------------

struct HashIndex {
    int64_t* keys;       // EMPTY = sentinel
    int32_t* slots;
    int64_t cap;         // power of two
    int64_t size;
    // INT64_MIN is the table sentinel, so that one key lives out-of-band
    // (remapping it would collide with INT64_MIN+1)
    int32_t min_key_slot;
    bool has_min_key;
};

static const int64_t EMPTY_KEY = INT64_MIN;

static inline uint64_t hash64(int64_t k) {
    uint64_t x = (uint64_t)k;
    x ^= x >> 33;
    x *= 0xFF51AFD7ED558CCDull;
    x ^= x >> 33;
    x *= 0xC4CEB9FE1A85EC53ull;
    x ^= x >> 33;
    return x;
}

void* hi_create(int64_t capacity) {
    int64_t cap = 16;
    while (cap < capacity * 2) cap <<= 1;  // keep load factor <= 0.5
    HashIndex* hi = (HashIndex*)std::malloc(sizeof(HashIndex));
    hi->keys = (int64_t*)std::malloc(cap * sizeof(int64_t));
    hi->slots = (int32_t*)std::malloc(cap * sizeof(int32_t));
    for (int64_t i = 0; i < cap; ++i) hi->keys[i] = EMPTY_KEY;
    hi->cap = cap;
    hi->size = 0;
    hi->min_key_slot = -1;
    hi->has_min_key = false;
    return hi;
}

void hi_free(void* p) {
    HashIndex* hi = (HashIndex*)p;
    std::free(hi->keys);
    std::free(hi->slots);
    std::free(hi);
}

int64_t hi_size(void* p) { return ((HashIndex*)p)->size; }

static void hi_grow(HashIndex* hi) {
    int64_t old_cap = hi->cap;
    int64_t* old_keys = hi->keys;
    int32_t* old_slots = hi->slots;
    hi->cap <<= 1;
    hi->keys = (int64_t*)std::malloc(hi->cap * sizeof(int64_t));
    hi->slots = (int32_t*)std::malloc(hi->cap * sizeof(int32_t));
    for (int64_t i = 0; i < hi->cap; ++i) hi->keys[i] = EMPTY_KEY;
    uint64_t mask = hi->cap - 1;
    for (int64_t i = 0; i < old_cap; ++i) {
        if (old_keys[i] == EMPTY_KEY) continue;
        uint64_t j = hash64(old_keys[i]) & mask;
        while (hi->keys[j] != EMPTY_KEY) j = (j + 1) & mask;
        hi->keys[j] = old_keys[i];
        hi->slots[j] = old_slots[i];
    }
    std::free(old_keys);
    std::free(old_slots);
}

// lookup-or-insert: out_slots[i] = dense slot of keys[i] (new slots assigned
// in first-seen order continuing from the current size)
void hi_upsert_batch(void* p, const int64_t* keys, int64_t n,
                     int32_t* out_slots) {
    HashIndex* hi = (HashIndex*)p;
    for (int64_t i = 0; i < n; ++i) {
        if (keys[i] == EMPTY_KEY) {
            if (!hi->has_min_key) {
                hi->has_min_key = true;
                hi->min_key_slot = (int32_t)hi->size++;
            }
            out_slots[i] = hi->min_key_slot;
            continue;
        }
        if (hi->size * 2 >= hi->cap) hi_grow(hi);
        uint64_t mask = hi->cap - 1;
        int64_t k = keys[i];
        uint64_t j = hash64(k) & mask;
        while (true) {
            if (hi->keys[j] == EMPTY_KEY) {
                hi->keys[j] = k;
                hi->slots[j] = (int32_t)hi->size++;
                out_slots[i] = hi->slots[j];
                break;
            }
            if (hi->keys[j] == k) {
                out_slots[i] = hi->slots[j];
                break;
            }
            j = (j + 1) & mask;
        }
    }
}

// lookup only: -1 for absent keys
void hi_lookup_batch(void* p, const int64_t* keys, int64_t n,
                     int32_t* out_slots) {
    HashIndex* hi = (HashIndex*)p;
    uint64_t mask = hi->cap - 1;
    for (int64_t i = 0; i < n; ++i) {
        if (keys[i] == EMPTY_KEY) {
            out_slots[i] = hi->has_min_key ? hi->min_key_slot : -1;
            continue;
        }
        int64_t k = keys[i];
        uint64_t j = hash64(k) & mask;
        out_slots[i] = -1;
        while (hi->keys[j] != EMPTY_KEY) {
            if (hi->keys[j] == k) {
                out_slots[i] = hi->slots[j];
                break;
            }
            j = (j + 1) & mask;
        }
    }
}

}  // extern "C"
