"""CLI: run pipelines and inspect savepoints from the command line.

Analog of the reference CliFrontend (flink-clients CliFrontend.java:92):

    python -m flink_tpu.cli run <script.py> [--parallelism N]
                                            [--state-backend NAME]
                                            [--checkpoint-dir DIR]
                                            [--checkpoint-interval SECS]
                                            [--from-savepoint PATH]
    python -m flink_tpu.cli savepoint-info <path>
    python -m flink_tpu.cli version

``run`` executes a user script that builds a pipeline on
StreamExecutionEnvironment.get_default() — the CLI pre-configures that
environment from the flags (parallelism, backend, checkpointing, savepoint
restore), mirroring how the reference CLI injects configuration into the
user program's environment.
"""

from __future__ import annotations

import argparse
import runpy
import sys
from typing import Optional

__all__ = ["main"]


def _cmd_run(args) -> int:
    from .api.environment import StreamExecutionEnvironment
    from .core.config import CheckpointingOptions, StateOptions

    env = StreamExecutionEnvironment.get_default()
    if args.parallelism:
        env.set_parallelism(args.parallelism)
    if args.state_backend:
        env.config.set(StateOptions.BACKEND, args.state_backend)
    if args.checkpoint_dir:
        env.config.set(CheckpointingOptions.DIRECTORY, args.checkpoint_dir)
    if args.checkpoint_interval:
        env.config.set(CheckpointingOptions.INTERVAL,
                       args.checkpoint_interval)
    if args.from_savepoint:
        env.restore_from_savepoint(args.from_savepoint)
    if args.target:
        # submit to a running session cluster instead of running in-process
        env.set_remote_target(args.target)
    try:
        runpy.run_path(args.script, run_name="__main__")
    except SystemExit as e:
        if e.code is None:
            return 0
        if isinstance(e.code, int):
            return e.code
        print(e.code, file=sys.stderr)  # sys.exit("message") idiom
        return 1
    return 0


def _cmd_savepoint_info(args) -> int:
    from .checkpoint.storage import (
        CheckpointNotFoundError, CorruptArtifactError,
    )
    from .state_processor import SavepointReader

    try:
        reader = SavepointReader.read(args.path)
    except CorruptArtifactError as e:
        print(f"savepoint-info: corrupt savepoint artifact at "
              f"{args.path}: {e}", file=sys.stderr)
        return 1
    except (CheckpointNotFoundError, FileNotFoundError, NotADirectoryError):
        print(f"savepoint-info: no savepoint at {args.path}",
              file=sys.stderr)
        return 1
    cp = reader.checkpoint
    print(f"savepoint id={cp.checkpoint_id} "
          f"savepoint={cp.is_savepoint} path={cp.external_path}")
    for vertex in reader.vertices():
        par = cp.vertex_parallelism.get(vertex, "?")
        uid = (cp.vertex_uids or {}).get(vertex, "")
        print(f"  vertex {vertex} parallelism={par} uid={uid}")
        for op_key in reader.operators(vertex).get(vertex, []):
            names = reader.state_names(vertex, op_key)
            print(f"    operator {op_key!r} keyed-states={names}")
    return 0


def _cmd_checkpoint_verify(args) -> int:
    """Offline artifact verification of every retained checkpoint under a
    storage directory (the restore-time verification, runnable before an
    incident): per-checkpoint OK/CORRUPT table from the manifest's chunk
    digests + metadata checksum. Exit code reflects the worst result —
    0 all OK, 1 any CORRUPT, 2 nothing to verify."""
    import os

    from .checkpoint.storage import (
        CheckpointNotFoundError, CorruptArtifactError, FsCheckpointStorage,
        retained_checkpoint_dirs,
    )

    if not os.path.isdir(args.dir):
        print(f"checkpoint-verify: no such directory: {args.dir}",
              file=sys.stderr)
        return 2
    storage = FsCheckpointStorage(args.dir)
    rows, worst = [], 0
    for _cid, path in retained_checkpoint_dirs(args.dir):
        name = os.path.basename(path)
        try:
            info = storage.verify_checkpoint(path)
            detail = f"{info['chunks']} chunks, {info['bytes']} bytes"
            if not info["manifest"]:
                detail += " (legacy: no manifest, deep-verified)"
            rows.append([name, "OK", detail])
        except (CorruptArtifactError, CheckpointNotFoundError) as e:
            rows.append([name, "CORRUPT", str(e)])
            worst = 1
    for name in sorted(os.listdir(args.dir)):
        if ".corrupt" in name and os.path.isdir(
                os.path.join(args.dir, name)):
            rows.append([name, "QUARANTINED", "previously failed "
                                              "verification"])
    # a co-located AOT executable cache (aot.dir pointed under the
    # checkpoint root) is verified in the same sweep
    aot_sub = os.path.join(args.dir, "aot")
    if os.path.isdir(aot_sub):
        from .runtime.aot import verify_aot_cache
        for name, status, detail in verify_aot_cache(aot_sub):
            rows.append([f"aot/{name}", status, detail])
            if status == "CORRUPT":
                worst = 1
    if not rows:
        print(f"no retained checkpoints under {args.dir}")
        return 2
    _print_table(["checkpoint", "status", "detail"], rows, max_rows=10_000)
    return worst


def _cmd_aot_cache(args) -> int:
    """Offline verification of a persistent AOT executable cache
    directory (``aot.dir``): per-artifact OK/CORRUPT/QUARANTINED table
    from the embedded header digests + environment fingerprint. Exit
    code reflects the worst result — 0 all OK, 1 any CORRUPT, 2 nothing
    to verify."""
    import os

    from .runtime.aot import verify_aot_cache

    if not os.path.isdir(args.dir):
        print(f"aot-cache: no such directory: {args.dir}", file=sys.stderr)
        return 2
    rows = [list(r) for r in verify_aot_cache(args.dir)]
    if not rows:
        print(f"no AOT artifacts under {args.dir}")
        return 2
    _print_table(["artifact", "status", "detail"], rows, max_rows=10_000)
    return 1 if any(r[1] == "CORRUPT" for r in rows) else 0


def _cmd_list(args) -> int:
    from .cluster.dispatcher import ClusterClient

    for job in ClusterClient(args.target).list_jobs():
        print(f"{job['job_id']}  {job['state']:<10} {job['name']}")
    return 0


def _cmd_cancel(args) -> int:
    from .cluster.dispatcher import ClusterClient

    ClusterClient(args.target).cancel(args.job_id)
    print(f"cancelled {args.job_id}")
    return 0


def _cmd_savepoint(args) -> int:
    from .cluster.dispatcher import ClusterClient

    sp = ClusterClient(args.target).trigger_savepoint(args.job_id)
    print(f"savepoint {sp['id']} path={sp.get('external_path')}")
    return 0


def _split_statements(text: str) -> list[str]:
    """Split on ';' OUTSIDE single-quoted SQL string literals ('' escapes
    a quote inside a literal). Returns complete statements; a trailing
    unterminated fragment is returned last un-split."""
    out, buf, in_str = [], [], False
    i = 0
    while i < len(text):
        ch = text[i]
        if in_str:
            if ch == "'":
                if i + 1 < len(text) and text[i + 1] == "'":
                    buf.append("''")
                    i += 2
                    continue
                in_str = False
            buf.append(ch)
        elif ch == "'":
            in_str = True
            buf.append(ch)
        elif ch == ";":
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        i += 1
    if "".join(buf).strip():
        out.append("".join(buf))
    return [s for s in out if s.strip()]


def _read_statements(args):
    """Yield complete ';'-terminated SQL statements from -e, -f, or an
    interactive prompt (reference SqlClient's statement splitter);
    semicolons inside quoted literals do not split."""
    if args.execute:
        yield from _split_statements(args.execute)
        return
    if args.file:
        with open(args.file) as f:
            yield from _split_statements(f.read())
        return
    try:
        import readline  # noqa: F401 - line editing when available
    except ImportError:
        pass
    print("Flink-TPU SQL client. Statements end with ';' — "
          "'quit;' exits.", flush=True)
    buf: list[str] = []
    while True:
        try:
            line = input("sql> " if not buf else "   > ")
        except (EOFError, KeyboardInterrupt):
            print()
            return
        buf.append(line)
        joined = "\n".join(buf)
        if ";" not in joined:
            continue
        parts = _split_statements(joined)
        complete = (joined.rstrip().endswith(";")
                    and (not parts or parts[-1].count("'") % 2 == 0))
        tail = None if complete else (parts.pop() if parts else None)
        for stmt in parts:
            if stmt.strip().lower() in ("quit", "exit"):
                return
            yield stmt
        buf = [tail] if tail else []


def _print_table(schema_names, rows, max_rows: int) -> None:
    shown = rows[:max_rows]
    cells = [[str(v) for v in r] for r in shown]
    widths = [max([len(n)] + [len(c[i]) for c in cells])
              for i, n in enumerate(schema_names)]

    def line(vals):
        return "| " + " | ".join(v.ljust(w)
                                 for v, w in zip(vals, widths)) + " |"

    sep = "+-" + "-+-".join("-" * w for w in widths) + "-+"
    print(sep)
    print(line(schema_names))
    print(sep)
    for c in cells:
        print(line(c))
    print(sep)
    extra = len(rows) - len(shown)
    tail = f" ({extra} more)" if extra > 0 else ""
    print(f"{len(rows)} row(s){tail}", flush=True)


def _cmd_trace_dump(args) -> int:
    """Fetch retained spans from a running endpoint's
    ``/jobs/<name>/traces`` and either write them as Chrome trace-event
    JSON (``-o`` — load the file in Perfetto / chrome://tracing) or
    print a span table. Falls back to THIS process's tracer when no
    ``--target`` is given (useful right after an in-process run)."""
    import json as _json
    import urllib.request

    from .metrics.tracing import Span, TRACER, chrome_trace_events

    if args.target:
        url = f"http://{args.target}/jobs/{args.job}/traces"
        try:
            with urllib.request.urlopen(url, timeout=10.0) as resp:
                payload = _json.loads(resp.read().decode())
        except OSError as e:
            print(f"trace-dump: cannot fetch {url}: {e}", file=sys.stderr)
            return 1
        spans = [Span(scope=d["scope"], name=d["name"],
                      start_ms=d["start_ms"], end_ms=d["end_ms"],
                      attributes=d.get("attributes") or {},
                      trace_id=d.get("trace_id", ""),
                      span_id=d.get("span_id", ""),
                      parent_id=d.get("parent_id", ""))
                 for d in payload.get("spans", [])]
    else:
        spans = TRACER.retained_spans()
    if args.output:
        with open(args.output, "w") as f:
            _json.dump(chrome_trace_events(spans), f)
        print(f"wrote {len(spans)} span(s) to {args.output}")
        return 0
    rows = [[s.scope, s.name, s.start_ms, s.duration_ms, s.trace_id,
             s.parent_id or "-"] for s in spans]
    _print_table(["scope", "name", "start_ms", "dur_ms", "trace", "parent"],
                 rows, max_rows=args.max_rows)
    return 0


def _cmd_state_residency(args) -> int:
    """Print the per-key-group residency/heat table of a job's tiered
    keyed state: which key groups are device-hot vs host-warm, their 2Q
    stage, decayed heat, and last-touch batch. Fetches
    ``/jobs/<name>/state-residency`` from a running endpoint, or falls
    back to THIS process's residency registry when no ``--target`` is
    given (useful right after an in-process run)."""
    import json as _json
    import urllib.request

    if args.target:
        url = f"http://{args.target}/jobs/{args.job}/state-residency"
        try:
            with urllib.request.urlopen(url, timeout=10.0) as resp:
                payload = _json.loads(resp.read().decode())
        except OSError as e:
            print(f"state-residency: cannot fetch {url}: {e}",
                  file=sys.stderr)
            return 1
        rows = payload.get("rows", [])
        series = payload.get("hit_ratio_series", {})
    else:
        from .state.tiering import hit_ratio_series, residency_table
        rows = residency_table(args.job)
        series = hit_ratio_series(args.job)
    if not rows:
        print("no tiered state registered (is the job running under "
              "state.backend.tpu.hbm-budget-bytes / -slots?)")
        return 0
    warm = sum(1 for r in rows if r["tier"] == "warm")
    cells = [[r["operator"], r["key_group"], r["tier"], r["stage"],
              r["warm_keys"], r["heat"], r["last_touch"]] for r in rows]
    _print_table(["operator", "key_group", "tier", "stage", "warm_keys",
                  "heat", "last_touch"], cells, max_rows=args.max_rows)
    print(f"{warm} warm / {len(rows) - warm} hot key group(s)")
    # per-boundary hot-hit-ratio trajectory (last boundaries, oldest
    # first): the cumulative tier_hot_hit_ratio gauge hides phase
    # changes — a paging storm shows up here as a dip
    for op, vals in sorted(series.items()):
        if vals:
            print(f"hit_ratio[{op}] last {len(vals)} boundar(y/ies): "
                  + " ".join(f"{v:.2f}" for v in vals))
    return 0


def _cmd_profile(args) -> int:
    """Print a job's device-time ledger profile: top-K hot programs
    (device-time share, percentiles, cost-model achieved-vs-estimated),
    per-operator device-time shares, and recompile-attribution records
    naming the argument that changed. Fetches ``/jobs/<name>/profile``
    from a running endpoint, or falls back to THIS process's ledger when
    no ``--target`` is given (useful right after an in-process run with
    profiler.enabled)."""
    import json as _json
    import urllib.request

    if args.target:
        url = (f"http://{args.target}/jobs/{args.job}/profile"
               f"?top={args.top}")
        try:
            with urllib.request.urlopen(url, timeout=10.0) as resp:
                payload = _json.loads(resp.read().decode())
        except OSError as e:
            print(f"profile: cannot fetch {url}: {e}", file=sys.stderr)
            return 1
    else:
        from .metrics.profiler import DEVICE_LEDGER
        payload = DEVICE_LEDGER.profile(job=args.job or None, top=args.top)
    if args.json:
        print(_json.dumps(payload, indent=2, sort_keys=True))
        return 0
    if not payload.get("enabled"):
        print("device-time ledger is disabled (run with "
              "profiler.enabled: true)")
    progs = payload.get("programs", [])
    if not progs:
        print("no attributed device time recorded")
        return 0
    print(f"job {payload.get('job') or '<all>'}: "
          f"{payload.get('total_device_ms', 0.0):.2f} ms device, "
          f"{payload.get('total_compile_ms', 0.0):.2f} ms compile")

    def _fmt(v, spec=".3f"):
        return format(v, spec) if isinstance(v, (int, float)) else "-"

    rows = [[p["site"], p["operator"] or "-", p["count"],
             _fmt(p["self_ms"], ".2f"), _fmt(p["p50_ms"]),
             _fmt(p["p95_ms"]), _fmt(p["max_ms"]),
             f"{p['share'] * 100:.1f}%", _fmt(p.get("est_ms")),
             _fmt(p.get("achieved_vs_estimated"), ".2f")] for p in progs]
    _print_table(["site", "operator", "n", "self_ms", "p50", "p95",
                  "max", "share", "est_ms", "ach/est"], rows,
                 max_rows=args.top)
    ops = payload.get("operators", [])
    if ops:
        _print_table(["operator", "device_ms", "share"],
                     [[o["operator"] or "-", _fmt(o["device_ms"], ".2f"),
                       f"{o['share'] * 100:.1f}%"] for o in ops],
                     max_rows=args.top)
    for r in payload.get("recompiles", []):
        changed = "; ".join(r.get("changed") or ()) or "<no arg diff>"
        print(f"recompile {r['site']}: {changed}")
    return 0


def _cmd_jobs(args) -> int:
    """Print the per-job quota/bulkhead table (``--quotas``): fair-share
    weight, remaining deficit, device-time share from the ledger,
    breaker state, and the rejected/shed counters. Fetches
    ``/jobs/<name>/quota`` for each job on a running endpoint, or falls
    back to THIS process's isolation scheduler when no ``--target`` is
    given (useful right after an in-process multi-job run)."""
    import json as _json
    import urllib.request

    if args.target:
        base = f"http://{args.target}"
        try:
            with urllib.request.urlopen(f"{base}/jobs",
                                        timeout=10.0) as resp:
                overview = _json.loads(resp.read().decode())
        except OSError as e:
            print(f"jobs: cannot fetch {base}/jobs: {e}", file=sys.stderr)
            return 1
        if isinstance(overview, dict):
            overview = overview.get("jobs", [])
        names = [j.get("name") for j in overview if j.get("name")]
        views = []
        for name in names:
            try:
                with urllib.request.urlopen(f"{base}/jobs/{name}/quota",
                                            timeout=10.0) as resp:
                    views.append(_json.loads(resp.read().decode()))
            except OSError as e:
                print(f"jobs: cannot fetch quota for {name}: {e}",
                      file=sys.stderr)
                return 1
        enabled = any(v.get("enabled") for v in views)
        views = [v for v in views if v.get("job")]
    else:
        from .cluster.isolation import ISOLATION
        snap = ISOLATION.snapshot()
        enabled = snap["enabled"]
        views = list(snap["jobs"].values())
    if not args.quotas:
        _print_table(["job"], [[v["job"]] for v in views],
                     max_rows=args.max_rows)
        return 0
    if not enabled:
        print("isolation is disabled (run with isolation.enabled: true)")
    if not views:
        print("no jobs registered with the isolation scheduler")
        return 0
    rows = [[v["job"], v["weight"], v["deficit"],
             f"{v['device_time_share'] * 100:.1f}%", v["breaker"],
             v["admitted_total"], v["admissions_rejected_total"],
             v["shed_records_total"], v["bulkhead_trips_total"]]
            for v in views]
    _print_table(["job", "weight", "deficit", "device_share", "breaker",
                  "admitted", "rejected", "shed_records", "trips"],
                 rows, max_rows=args.max_rows)
    return 0


def _cmd_sql(args) -> int:
    """Interactive SQL client against a TableEnvironment (reference
    flink-table/flink-sql-client SqlClient.java:67): DDL mutates the
    session catalog; queries run and render their FINAL table (changelog
    folded). ``--target`` submits query jobs to a session cluster."""
    from .api.environment import StreamExecutionEnvironment
    from .core.config import StateOptions
    from .sql import TableEnvironment
    from .sql import rowkind as rk

    env = StreamExecutionEnvironment()
    if args.parallelism:
        env.set_parallelism(args.parallelism)
    if args.state_backend:
        env.config.set(StateOptions.BACKEND, args.state_backend)
    if args.target:
        env.set_remote_target(args.target)
    t_env = TableEnvironment(env)
    rc = 0
    for stmt in _read_statements(args):
        try:
            res = t_env.execute_sql(stmt)
        except Exception as e:  # the REPL survives bad statements
            print(f"[ERROR] {e}", file=sys.stderr, flush=True)
            if args.execute or args.file:
                return 1       # script mode: fail fast, fail loudly
            continue           # interactive: keep the session alive
        names = [n for n in res.schema.names if n != rk.ROWKIND_COLUMN]
        rows = res.collect_final()
        if names == ["result"] and rows in ([("OK",)], [["OK"]]):
            print("[INFO] OK", flush=True)
        else:
            _print_table(names, rows, args.max_rows)
    return rc


def _cmd_sql_gateway(args) -> int:
    """Serve the REST SQL gateway (reference SqlGatewayRestEndpoint)."""
    import time

    from .sql.gateway import SqlGateway

    gw = SqlGateway(port=args.port, host=args.host,
                    state_backend=args.state_backend)
    gw.start()
    print(f"sql gateway listening on {args.host}:{gw.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        gw.stop()
        return 0


def _cmd_deploy(args) -> int:
    """Launch one SPMD script across N supervised worker processes
    (reference start-cluster.sh + active resource manager drivers; see
    cluster/deployment.py — a Kubernetes driver swaps the process
    launcher for pod creation)."""
    from .cluster.deployment import ProcessDeploymentDriver, SpmdDeployment

    dep = SpmdDeployment(
        args.script, n_hosts=args.hosts,
        driver=ProcessDeploymentDriver(stdout_dir=args.log_dir or None),
        max_worker_restarts=args.max_restarts)
    dep.start()
    print(f"deployed {args.hosts} workers; supervising", flush=True)
    try:
        codes = dep.wait(timeout=args.timeout)
    except KeyboardInterrupt:
        dep.stop()          # never orphan worker processes on Ctrl-C
        print("interrupted; workers stopped", flush=True)
        return 130
    for hid in sorted(codes):
        print(f"worker {hid}: exit {codes[hid]}")
    return 0 if all(c == 0 for c in codes.values()) else 1


def _cmd_cluster(args) -> int:
    import time

    from .cluster.dispatcher import Dispatcher

    d = Dispatcher(port=args.port, host=args.host,
                   archive_dir=args.archive_dir or None)
    port = d.start()
    print(f"session cluster listening on {args.host}:{port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        d.stop()
        return 0


def _cmd_plan(args) -> int:
    """Print the fusion certificate for a pipeline script or .sql file:
    per chained vertex the operator chain, its verdict (CERTIFIED /
    PARTIAL / REJECTED), whether the runtime lowers the prefix to one
    dispatch, and every rejecting PLAN6xx finding with file:line (the
    catalogue lives in docs/ANALYSIS.md). Execution is stubbed — the
    script's graphs compile and certify but never run."""
    import json as _json

    from .graph.fusion import capture_certificates

    certs, err = capture_certificates(args.script, argv=args.args)
    if err:
        print(f"plan: script error after capture: {err}", file=sys.stderr)
    if not certs:
        print("plan: the script built no pipeline (nothing to certify)",
              file=sys.stderr)
        return 2
    if args.json:
        print(_json.dumps([c.to_dict() for c in certs], indent=2,
                          sort_keys=True))
        return 0
    for cert in certs:
        print(f"job {cert.job_name!r} "
              f"fusion_enabled={cert.fusion_enabled}")
        rows = []
        for ch in cert.chains:
            ops = " -> ".join(f"{o.name}[{o.category}]" for o in ch.ops)
            lowered = "one-dispatch" if ch.lowered_prefix else "-"
            if ch.findings:
                rejects = "; ".join(f"{f.rule} {f.file}:{f.line}"
                                    for f in ch.findings)
            else:
                rejects = "-"
            rows.append([ch.vertex_id, ops, ch.verdict, lowered, rejects])
        _print_table(["chain", "operators", "verdict", "lowered",
                      "rejected by"], rows, max_rows=1000)
        for ch in cert.chains:
            for f in ch.findings:
                print(f"  {f.rule} {f.file}:{f.line} [{f.symbol}] "
                      f"{f.message}")
    return 0


def _cmd_lint(args) -> int:
    """tpu-lint driver: Tier-A AST rules + Tier-B jaxpr program audit,
    diffed against the committed baseline (flink_tpu/analysis/
    baseline.json).  Exit 0 clean, 1 unbaselined/stale findings, 2
    usage error."""
    import json as _json

    from .analysis import (AnalysisContext, all_rules,
                           diff_against_baseline, run_rules,
                           save_baseline)

    known = all_rules()
    if args.rules:
        selected = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in selected if r not in known]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    else:
        selected = sorted(known)

    skipped: list[str] = []
    if any(known[r].tier == "B" for r in selected):
        # The jaxpr audit lints programs a pipeline actually built:
        # exercise a tiny Q5-shaped job to populate the registry.
        from .metrics.device import PROGRAM_AUDIT
        if not PROGRAM_AUDIT:
            try:
                from .analysis.jaxpr_rules import exercise_programs
                exercise_programs()
            except Exception as e:
                skipped.append(f"tier-B program exercise failed: {e}")

    ctx = AnalysisContext()
    findings = run_rules(ctx, selected, skipped)
    new, stale = diff_against_baseline(findings)

    if args.update_baseline:
        save_baseline(findings, default_reason=args.reason or None)
        if args.reason:
            print(f"baseline updated: {len(findings)} entries "
                  f"({len(new)} stamped with the given reason)")
        else:
            print(f"baseline updated: {len(findings)} entries "
                  f"({len(new)} need a reviewed reason)")
        return 0

    if args.json:
        print(_json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.fingerprint for f in new],
            "stale_baseline": stale,
            "skipped": skipped}, indent=2, sort_keys=True))
    else:
        new_fps = {f.fingerprint for f in new}
        if findings:
            rows = [[f.rule,
                     "NEW" if f.fingerprint in new_fps else "baselined",
                     f.location(), f.message] for f in findings]
            _print_table(["rule", "status", "location", "finding"],
                         rows, max_rows=200)
            for f in new:
                if f.hint:
                    print(f"  {f.rule} {f.location()}: hint: {f.hint}")
        for s in skipped:
            print(f"skipped: {s}")
        for e in stale:
            print(f"stale baseline entry (fixed? run --update-baseline): "
                  f"{e['rule']} {e['file']} {e['symbol']}")
        print(f"{len(findings)} finding(s), {len(new)} new, "
              f"{len(stale)} stale baseline entr(y/ies)")
    return 1 if (new or stale) else 0


def _cmd_leader(args) -> int:
    """Who currently leads the coordinator election over an HA dir
    (cluster/ha.py leader_info): leader owner, fencing epoch, lease age,
    published address, standby roster."""
    import json as _json

    from .cluster.ha import leader_info

    info = leader_info(args.ha_dir)
    if args.json:
        print(_json.dumps(info, indent=2, sort_keys=True))
        return 0 if info.get("leader") else 1
    leader = info.get("leader")
    if not leader:
        print(f"no leader for {args.ha_dir}")
        if info.get("standbys"):
            print(f"standbys ({info['standby_count']}): "
                  + ", ".join(info["standbys"]))
        return 1
    age = info.get("lease_age")
    print(f"leader:   {leader}")
    print(f"epoch:    {info.get('epoch')}")
    print(f"lease age: {age:.3f}s" if age is not None else "lease age: ?")
    if info.get("address"):
        print(f"address:  {info['address']}")
    print(f"standbys: {info.get('standby_count', 0)}"
          + (f" ({', '.join(info['standbys'])})"
             if info.get("standbys") else ""))
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="flink-tpu", description="flink-tpu command line client")
    sub = parser.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run a pipeline script")
    run.add_argument("script")
    run.add_argument("--parallelism", "-p", type=int, default=0)
    run.add_argument("--state-backend", default="")
    run.add_argument("--checkpoint-dir", default="")
    run.add_argument("--checkpoint-interval", type=float, default=0.0)
    run.add_argument("--from-savepoint", default="")
    run.add_argument("--target", default="",
                     help="host:port of a running session cluster "
                          "(flink-tpu cluster); empty = run locally")
    run.set_defaults(fn=_cmd_run)

    cluster = sub.add_parser(
        "cluster", help="start a standing session cluster (Dispatcher)")
    cluster.add_argument("--port", type=int, default=8081)
    cluster.add_argument("--host", default="127.0.0.1")
    cluster.add_argument("--archive-dir", default="")
    cluster.set_defaults(fn=_cmd_cluster)

    lst = sub.add_parser("list", help="list jobs on a session cluster")
    lst.add_argument("--target", required=True, help="host:port")
    lst.set_defaults(fn=_cmd_list)

    cancel = sub.add_parser("cancel", help="cancel a job on a cluster")
    cancel.add_argument("job_id")
    cancel.add_argument("--target", required=True)
    cancel.set_defaults(fn=_cmd_cancel)

    sp = sub.add_parser("savepoint",
                        help="trigger a savepoint on a running job")
    sp.add_argument("job_id")
    sp.add_argument("--target", required=True)
    sp.set_defaults(fn=_cmd_savepoint)

    spi = sub.add_parser("savepoint-info", help="inspect a savepoint")
    spi.add_argument("path")
    spi.set_defaults(fn=_cmd_savepoint_info)

    cvf = sub.add_parser(
        "checkpoint-verify",
        help="verify every retained checkpoint's artifact integrity "
             "offline (chunk digests + metadata checksum)")
    cvf.add_argument("dir", help="checkpoint storage directory "
                                 "(execution.checkpointing.dir)")
    cvf.set_defaults(fn=_cmd_checkpoint_verify)

    aotc = sub.add_parser(
        "aot-cache",
        help="verify a persistent AOT executable cache directory "
             "offline (artifact digests + environment fingerprint)")
    aotc.add_argument("dir", help="the cache directory (config key "
                                  "aot.dir)")
    aotc.set_defaults(fn=_cmd_aot_cache)

    trd = sub.add_parser(
        "trace-dump",
        help="dump causal-trace spans from a running job (or this "
             "process) as a table or Perfetto-loadable JSON")
    trd.add_argument("--target", default="",
                     help="host:port of a REST endpoint; empty = the "
                          "current process's tracer")
    trd.add_argument("--job", default="job",
                     help="job name on the endpoint (default: job)")
    trd.add_argument("-o", "--output", default="",
                     help="write Chrome trace-event JSON here instead of "
                          "printing a table")
    trd.add_argument("--max-rows", type=int, default=200)
    trd.set_defaults(fn=_cmd_trace_dump)

    srr = sub.add_parser(
        "state-residency",
        help="print the per-key-group residency/heat table of a job's "
             "tiered keyed state (device-hot vs host-warm)")
    srr.add_argument("job", nargs="?", default="",
                     help="job (or job/operator) name; empty = every "
                          "registered operator")
    srr.add_argument("--target", default="",
                     help="host:port of a REST endpoint; empty = the "
                          "current process's residency registry")
    srr.add_argument("--max-rows", type=int, default=200)
    srr.set_defaults(fn=_cmd_state_residency)

    prf = sub.add_parser(
        "profile",
        help="print a job's device-time ledger profile (hot programs, "
             "per-operator shares, recompile attribution)")
    prf.add_argument("job", nargs="?", default="",
                     help="job name; empty = every attributed job "
                          "(local fallback only)")
    prf.add_argument("--target", default="",
                     help="host:port of a REST endpoint; empty = the "
                          "current process's ledger")
    prf.add_argument("--top", type=int, default=10,
                     help="programs to show (default 10)")
    prf.add_argument("--json", action="store_true",
                     help="machine-readable payload")
    prf.set_defaults(fn=_cmd_profile)

    jbs = sub.add_parser(
        "jobs",
        help="list jobs; --quotas adds the per-job admission-quota / "
             "bulkhead table (weight, deficit, device share, breaker)")
    jbs.add_argument("--quotas", action="store_true",
                     help="show the isolation scheduler's quota columns")
    jbs.add_argument("--target", default="",
                     help="host:port of a REST endpoint; empty = the "
                          "current process's isolation scheduler")
    jbs.add_argument("--max-rows", type=int, default=50)
    jbs.set_defaults(fn=_cmd_jobs)

    gwp = sub.add_parser("sql-gateway",
                         help="serve the REST SQL gateway")
    gwp.add_argument("--port", type=int, default=8083)
    gwp.add_argument("--host", default="127.0.0.1")
    gwp.add_argument("--state-backend", default="")
    gwp.set_defaults(fn=_cmd_sql_gateway)

    dep = sub.add_parser(
        "deploy", help="run an SPMD script across N supervised workers")
    dep.add_argument("script")
    dep.add_argument("--hosts", type=int, default=2)
    dep.add_argument("--log-dir", default="")
    dep.add_argument("--max-restarts", type=int, default=2)
    dep.add_argument("--timeout", type=float, default=3600.0)
    dep.set_defaults(fn=_cmd_deploy)

    sql = sub.add_parser(
        "sql", help="interactive SQL client (reference sql-client.sh)")
    sql.add_argument("-e", "--execute", help="run statements and exit")
    sql.add_argument("-f", "--file", help="run a .sql script and exit")
    sql.add_argument("--target", help="session cluster host:port")
    sql.add_argument("--state-backend", default="")
    sql.add_argument("--parallelism", type=int, default=0)
    sql.add_argument("--max-rows", type=int, default=100)
    sql.set_defaults(fn=_cmd_sql)

    lint = sub.add_parser(
        "lint", help="tpu-lint: device-path static analysis "
                     "(AST rules + jaxpr program audit)")
    lint.add_argument("--rules", help="comma-separated rule ids "
                                      "(default: all)")
    lint.add_argument("--json", action="store_true",
                      help="machine-readable output")
    lint.add_argument("--update-baseline", action="store_true",
                      help="rewrite flink_tpu/analysis/baseline.json "
                           "from the current findings")
    lint.add_argument("--reason", default="",
                      help="with --update-baseline: stamp NEW baseline "
                           "entries with this reviewed reason instead of "
                           "the TODO placeholder (BASE601 flags entries "
                           "whose reason is still the TODO)")
    lint.set_defaults(fn=_cmd_lint)

    plan = sub.add_parser(
        "plan", help="print the fusion certificate for an example "
                     "pipeline or .sql script (PLAN6xx rejections with "
                     "file:line; see docs/ANALYSIS.md)")
    plan.add_argument("script", help="a pipeline .py script or a .sql file")
    plan.add_argument("--json", action="store_true",
                      help="machine-readable certificate")
    plan.add_argument("args", nargs="*",
                      help="argv passed through to the script")
    plan.set_defaults(fn=_cmd_plan)

    ldr = sub.add_parser(
        "leader", help="print the current coordinator-election leader "
                       "of an HA dir (owner, fencing epoch, lease age, "
                       "standby count)")
    ldr.add_argument("ha_dir", help="the job's ha.dir")
    ldr.add_argument("--json", action="store_true",
                     help="machine-readable payload")
    ldr.set_defaults(fn=_cmd_leader)

    ver = sub.add_parser("version", help="print version")
    ver.set_defaults(fn=lambda a: (print("flink-tpu 0.1"), 0)[1])

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
