"""CLI: run pipelines and inspect savepoints from the command line.

Analog of the reference CliFrontend (flink-clients CliFrontend.java:92):

    python -m flink_tpu.cli run <script.py> [--parallelism N]
                                            [--state-backend NAME]
                                            [--checkpoint-dir DIR]
                                            [--checkpoint-interval SECS]
                                            [--from-savepoint PATH]
    python -m flink_tpu.cli savepoint-info <path>
    python -m flink_tpu.cli version

``run`` executes a user script that builds a pipeline on
StreamExecutionEnvironment.get_default() — the CLI pre-configures that
environment from the flags (parallelism, backend, checkpointing, savepoint
restore), mirroring how the reference CLI injects configuration into the
user program's environment.
"""

from __future__ import annotations

import argparse
import runpy
import sys
from typing import Optional

__all__ = ["main"]


def _cmd_run(args) -> int:
    from .api.environment import StreamExecutionEnvironment
    from .core.config import CheckpointingOptions, StateOptions

    env = StreamExecutionEnvironment.get_default()
    if args.parallelism:
        env.set_parallelism(args.parallelism)
    if args.state_backend:
        env.config.set(StateOptions.BACKEND, args.state_backend)
    if args.checkpoint_dir:
        env.config.set(CheckpointingOptions.DIRECTORY, args.checkpoint_dir)
    if args.checkpoint_interval:
        env.config.set(CheckpointingOptions.INTERVAL,
                       args.checkpoint_interval)
    if args.from_savepoint:
        env.restore_from_savepoint(args.from_savepoint)
    if args.target:
        # submit to a running session cluster instead of running in-process
        env.set_remote_target(args.target)
    try:
        runpy.run_path(args.script, run_name="__main__")
    except SystemExit as e:
        if e.code is None:
            return 0
        if isinstance(e.code, int):
            return e.code
        print(e.code, file=sys.stderr)  # sys.exit("message") idiom
        return 1
    return 0


def _cmd_savepoint_info(args) -> int:
    from .state_processor import SavepointReader

    reader = SavepointReader.read(args.path)
    cp = reader.checkpoint
    print(f"savepoint id={cp.checkpoint_id} "
          f"savepoint={cp.is_savepoint} path={cp.external_path}")
    for vertex in reader.vertices():
        par = cp.vertex_parallelism.get(vertex, "?")
        uid = (cp.vertex_uids or {}).get(vertex, "")
        print(f"  vertex {vertex} parallelism={par} uid={uid}")
        for op_key in reader.operators(vertex).get(vertex, []):
            names = reader.state_names(vertex, op_key)
            print(f"    operator {op_key!r} keyed-states={names}")
    return 0


def _cmd_list(args) -> int:
    from .cluster.dispatcher import ClusterClient

    for job in ClusterClient(args.target).list_jobs():
        print(f"{job['job_id']}  {job['state']:<10} {job['name']}")
    return 0


def _cmd_cancel(args) -> int:
    from .cluster.dispatcher import ClusterClient

    ClusterClient(args.target).cancel(args.job_id)
    print(f"cancelled {args.job_id}")
    return 0


def _cmd_savepoint(args) -> int:
    from .cluster.dispatcher import ClusterClient

    sp = ClusterClient(args.target).trigger_savepoint(args.job_id)
    print(f"savepoint {sp['id']} path={sp.get('external_path')}")
    return 0


def _cmd_cluster(args) -> int:
    import time

    from .cluster.dispatcher import Dispatcher

    d = Dispatcher(port=args.port, host=args.host,
                   archive_dir=args.archive_dir or None)
    port = d.start()
    print(f"session cluster listening on {args.host}:{port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        d.stop()
        return 0


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="flink-tpu", description="flink-tpu command line client")
    sub = parser.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run a pipeline script")
    run.add_argument("script")
    run.add_argument("--parallelism", "-p", type=int, default=0)
    run.add_argument("--state-backend", default="")
    run.add_argument("--checkpoint-dir", default="")
    run.add_argument("--checkpoint-interval", type=float, default=0.0)
    run.add_argument("--from-savepoint", default="")
    run.add_argument("--target", default="",
                     help="host:port of a running session cluster "
                          "(flink-tpu cluster); empty = run locally")
    run.set_defaults(fn=_cmd_run)

    cluster = sub.add_parser(
        "cluster", help="start a standing session cluster (Dispatcher)")
    cluster.add_argument("--port", type=int, default=8081)
    cluster.add_argument("--host", default="127.0.0.1")
    cluster.add_argument("--archive-dir", default="")
    cluster.set_defaults(fn=_cmd_cluster)

    lst = sub.add_parser("list", help="list jobs on a session cluster")
    lst.add_argument("--target", required=True, help="host:port")
    lst.set_defaults(fn=_cmd_list)

    cancel = sub.add_parser("cancel", help="cancel a job on a cluster")
    cancel.add_argument("job_id")
    cancel.add_argument("--target", required=True)
    cancel.set_defaults(fn=_cmd_cancel)

    sp = sub.add_parser("savepoint",
                        help="trigger a savepoint on a running job")
    sp.add_argument("job_id")
    sp.add_argument("--target", required=True)
    sp.set_defaults(fn=_cmd_savepoint)

    spi = sub.add_parser("savepoint-info", help="inspect a savepoint")
    spi.add_argument("path")
    spi.set_defaults(fn=_cmd_savepoint_info)

    ver = sub.add_parser("version", help="print version")
    ver.set_defaults(fn=lambda a: (print("flink-tpu 0.1"), 0)[1])

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
