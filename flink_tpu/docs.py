"""Configuration reference generator.

Reference: flink-docs ConfigOptionsDocGenerator.java:69 — the config
reference pages are generated from the ``ConfigOption`` definitions in
code, so docs can never drift from behavior. Same here: this walks every
``*Options`` class in core/config.py and emits a markdown table per class.

    python -m flink_tpu.docs [output.md]     # default: docs/CONFIG.md
"""

from __future__ import annotations

import inspect
import sys
from typing import Any

from .core import config as _config
from .core.config import ConfigOption

__all__ = ["generate_config_docs"]


def _fmt_default(opt: ConfigOption) -> str:
    d = opt.default
    if d is None:
        return "(none)"
    if isinstance(d, str):
        return f'`"{d}"`' if d else '`""`'
    return f"`{d}`"


def _fmt_type(opt: ConfigOption) -> str:
    if opt.semantic:
        return opt.semantic
    return getattr(opt.type, "__name__", str(opt.type))


def generate_config_docs() -> str:
    out = ["# Configuration reference",
           "",
           "Generated from `flink_tpu/core/config.py` "
           "(`python -m flink_tpu.docs`). Every option is a typed "
           "`ConfigOption` (reference ConfigOption.java:42); docs cannot "
           "drift from code.", ""]
    for name, cls in inspect.getmembers(_config, inspect.isclass):
        if not name.endswith("Options"):
            continue
        opts = [(attr, val) for attr, val in vars(cls).items()
                if isinstance(val, ConfigOption)]
        if not opts:
            continue
        out.append(f"## {name}")
        doc = inspect.getdoc(cls)
        if doc:
            out.append("")
            # first PARAGRAPH, whitespace-joined (a wrapped summary line
            # must not truncate mid-sentence)
            first_para = doc.split("\n\n")[0]
            out.append(" ".join(first_para.split()))
        out.append("")
        out.append("| Key | Type | Default | Description |")
        out.append("|---|---|---|---|")
        for _attr, opt in sorted(opts, key=lambda kv: kv[1].key):
            # '|' would split the markdown table cell
            desc = " ".join(opt.description.split()).replace("|", "\\|")
            out.append(f"| `{opt.key}` | {_fmt_type(opt)} | "
                       f"{_fmt_default(opt)} | {desc} |")
        out.append("")
    out.append(_REPORTERS_EPILOGUE)
    return "\n".join(out) + "\n"


# Hand-written epilogue appended by the generator so the narrative section
# survives regeneration (the tables above stay code-derived).
_REPORTERS_EPILOGUE = """\
## Configuring metric reporters

Reporters poll the job's `MetricRegistry` (reference `ReporterSetup`).
Select them by name with `metrics.reporters` (comma-separated):

```python
env.config.set("metrics.reporters", "prometheus,log")
reg = flink_tpu.metrics.MetricRegistry()
for rep in flink_tpu.metrics.reporters_from_config(env.config):
    rep.open(reg)          # PrometheusReporter binds an HTTP port here
env.execute("job", metrics_registry=reg)
```

Built-in names:

| Name | Class | Behavior |
|---|---|---|
| `prometheus` | `PrometheusReporter` | Serves `GET /metrics` in the text exposition format (pull model); `port=0` picks a free port, read it from `reporter.port`. |
| `log` | `LoggingReporter` | Dumps a registry snapshot every `metrics.reporter.interval` seconds to its `sink` (default `print`). |

Third-party reporters register under a name with
`flink_tpu.metrics.register_reporter(name, factory)` and are then
selectable through `metrics.reporters` like the built-ins.

Latency tracking: set `metrics.latency.interval` > 0 to inject
`LatencyMarker`s at sources; every operator records source->operator
latency into its `latency` histogram. The full metric catalog is in
`docs/OBSERVABILITY.md`.
"""


def main(argv: list[str]) -> int:
    target = argv[0] if argv else "docs/CONFIG.md"
    import os
    os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
    text = generate_config_docs()
    with open(target, "w") as f:
        f.write(text)
    n_rows = sum(1 for ln in text.splitlines() if ln.startswith("| `"))
    print(f"wrote {target}: {n_rows} options")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
