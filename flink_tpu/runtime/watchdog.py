"""Stall watchdog: deadline-bounded blocking operations + task-progress
supervision.

PR 2 made device *failures* survivable; this module makes *hangs*
survivable — a hung XLA execute, host<->device transfer, checkpoint
write, or control-plane send can no longer freeze a mailbox loop forever
with zero signal (the reference's liveness story: heartbeat + checkpoint
timeouts; SURVEY L3/L4 control plane treats liveness as a first-class
recovery input).

Two mechanisms:

* **Deadline-bounded calls** (``WATCHDOG.run`` / ``stall_bounded``):
  every blocking site — ``device.compile``, ``device.execute``,
  ``transfer.h2d/d2h``, ``checkpoint.write/load``, ``rpc.send``,
  ``bench.probe`` — runs on a supervised worker thread with a per-site
  configurable deadline (``watchdog.*`` config keys). Expiry abandons
  the worker and raises a typed :class:`StallError` to the caller, which
  feeds the PR-2 degradation ladder: a stall is transient (backoff-
  retry), repeated stalls at one site are persistent (state evacuation +
  CPU-fallback pin under ``DeviceGuard``, task failover elsewhere).
  Exactly-once is preserved because abandoned workers never execute the
  real operation after an injected hang (the hang sleep checks the
  abandonment flag), and the non-guarded wrapped regions are idempotent
  (pure uploads/materializations) so in-place retries are safe.

* **Task-progress supervision** (``TaskProgress`` +
  ``TaskStallDetector``): every mailbox loop bumps a per-subtask
  progress epoch; a job-level detector (started by ``run_job``, the
  ``JobSupervisor``, and each ``DistributedHost`` attempt) flags any
  subtask whose epoch has not advanced within ``task.stall-timeout``
  while its input gates hold queued data, and routes it into the
  existing failure->region-restart path by failing the task with a
  ``StallError``. This is the backstop for hangs the per-site deadlines
  cannot see (a wedged operator, an unwrapped third-party call).

Determinism: ``FaultInjector`` rules accept a ``!hang@MS`` flag — a
tripped hang rule *sleeps* MS milliseconds at the site instead of
raising, so every stall path is testable with tiny delays and replays
byte-identically by seed (same visit-order guarantee as every other
fault mode).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = ["StallError", "Watchdog", "WATCHDOG", "stall_bounded",
           "TaskProgress", "TaskStallDetector", "current_call_abandoned"]


class StallError(RuntimeError):
    """A supervised operation exceeded its deadline (or a task's progress
    epoch stalled). Transient for the degradation ladder: retry first,
    escalate on repetition."""

    def __init__(self, site: str, deadline_s: float,
                 scope: Optional[str] = None):
        where = f"{site}[{scope}]" if scope else site
        super().__init__(
            f"operation at {where} stalled past its "
            f"{deadline_s:.3g}s deadline")
        self.site = site
        self.deadline_s = deadline_s
        self.scope = scope


#: Thread-local marker for the watchdog worker running the current call,
#: consulted by the fault injector's hang sleep so an abandoned worker
#: never executes the real operation after its injected hang ends.
_TLS = threading.local()


def current_call_abandoned() -> bool:
    call = getattr(_TLS, "call", None)
    return call is not None and call.abandoned


class _Call:
    """One supervised invocation: result/exception slot + abandon flag."""

    __slots__ = ("fn", "done", "result", "exc", "abandoned")

    def __init__(self, fn: Callable):
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None
        self.abandoned = False

    def execute(self) -> None:
        _TLS.call = self
        try:
            self.result = self.fn()
        except BaseException as e:  # noqa: BLE001 - relayed to the caller
            self.exc = e
        finally:
            _TLS.call = None
            self.done.set()


class Watchdog:
    """Per-site deadline supervisor. One instance per process
    (``WATCHDOG``), configured from the job ``Configuration`` by the
    deploy paths exactly like ``FAULTS``."""

    #: site -> the WatchdogOptions attribute its deadline reads from
    _SITE_OPTIONS = {
        "device.compile": "COMPILE_TIMEOUT",
        "device.execute": "EXECUTE_TIMEOUT",
        "transfer.h2d": "TRANSFER_TIMEOUT",
        "transfer.d2h": "TRANSFER_TIMEOUT",
        "checkpoint.write": "CHECKPOINT_TIMEOUT",
        "checkpoint.load": "CHECKPOINT_TIMEOUT",
        "rpc.send": "RPC_TIMEOUT",
        "tier.evict": "TIER_TIMEOUT",
        "tier.prefetch": "TIER_TIMEOUT",
        "bench.probe": "PROBE_TIMEOUT",
        "aot.warmup": "AOT_WARMUP_TIMEOUT",  # lint: key-ok watchdog site label, not a config key
    }

    #: sites whose deadline reads from NetworkOptions instead (net.*
    #: keys live beside the other networking options; note the inverted
    #: zero convention — net.reconnect-timeout=0 DISABLES reconnection
    #: rather than unbounding it, enforced by the transport itself)
    _NET_SITE_OPTIONS = {
        "net.reconnect": "RECONNECT_TIMEOUT",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True
        self.deadlines: dict[str, float] = self._default_deadlines()
        self.stall_retries = 1
        self.trips: dict[str, int] = {}
        #: bounded stall-event log, merged into REST
        #: ``/jobs/<id>/exceptions`` (the JobExceptionsHandler analog for
        #: stalls that never reach a task failure — e.g. a stall absorbed
        #: by retry or by the degradation ladder)
        self.events: list[dict] = []

    @staticmethod
    def _default_deadlines() -> dict[str, float]:
        from ..core.config import NetworkOptions, WatchdogOptions

        out = {site: getattr(WatchdogOptions, attr).default
               for site, attr in Watchdog._SITE_OPTIONS.items()}
        out.update({site: getattr(NetworkOptions, attr).default
                    for site, attr in Watchdog._NET_SITE_OPTIONS.items()})
        return out

    # -- configuration ---------------------------------------------------
    def configure(self, config) -> None:
        """Adopt ``watchdog.*`` (and the ``net.reconnect`` site's
        ``net.*``) keys from a job Configuration."""
        from ..core.config import NetworkOptions, WatchdogOptions

        with self._lock:
            self.enabled = bool(config.get(WatchdogOptions.ENABLED))
            self.stall_retries = int(
                config.get(WatchdogOptions.STALL_RETRIES))
            for site, attr in self._SITE_OPTIONS.items():
                self.deadlines[site] = float(
                    config.get(getattr(WatchdogOptions, attr)))
            for site, attr in self._NET_SITE_OPTIONS.items():
                self.deadlines[site] = float(
                    config.get(getattr(NetworkOptions, attr)))

    def reset(self) -> None:
        """Back to defaults and clear trip accounting (test isolation)."""
        with self._lock:
            self.enabled = True
            self.deadlines = self._default_deadlines()
            self.stall_retries = 1
            self.trips.clear()
            self.events.clear()

    def deadline_for(self, site: str) -> float:
        return self.deadlines.get(site, 0.0)

    def trips_total(self) -> int:
        with self._lock:
            return sum(self.trips.values())

    # -- the supervised call ---------------------------------------------
    def run(self, site: str, fn: Callable, deadline: Optional[float] = None,
            scope: Optional[str] = None,
            on_stall: Optional[Callable] = None):
        """Run ``fn`` under ``site``'s deadline on a supervised worker;
        raise :class:`StallError` on expiry. Disabled watchdog or a
        zero/negative deadline calls through directly (no worker thread,
        no supervision)."""
        d = self.deadline_for(site) if deadline is None else deadline
        if not self.enabled or d is None or d <= 0:
            return fn()
        # the supervised worker is a fresh thread: re-pin the caller's
        # (job, operator) dispatch context so device-time ledger samples
        # recorded inside fn keep their attribution across the hop
        from ..metrics.profiler import dispatch_context, set_dispatch_context
        job, operator = dispatch_context()
        if job or operator:
            inner = fn

            def fn():
                set_dispatch_context(job, operator)
                return inner()

        call = _Call(fn)
        worker = threading.Thread(target=call.execute,
                                  name=f"watchdog:{site}", daemon=True)
        worker.start()
        if call.done.wait(d):
            if call.exc is not None:
                raise call.exc
            return call.result
        call.abandoned = True
        self._note_trip(site, scope, d)
        if on_stall is not None:
            try:
                on_stall()
            except Exception:  # noqa: BLE001 - best-effort cleanup hook
                pass
        raise StallError(site, d, scope)

    def note_stall(self, site: str, deadline: float,
                   scope: Optional[str] = None) -> StallError:
        """Record a deadline expiry observed by a caller that runs its
        own bounded retry loop instead of a supervised worker (the
        transport's reconnect path owns the socket lifecycle, so it
        cannot run under ``run``): counts the trip into the same
        events/metrics surface and returns the typed error for the
        caller to raise."""
        self._note_trip(site, scope, deadline)
        return StallError(site, deadline, scope)

    def _note_trip(self, site: str, scope: Optional[str],
                   deadline: float) -> None:
        # the owning job (thread-local dispatch context, pinned at task-
        # thread start): multi-job stall events/dumps must be attributable
        # to ONE tenant's failure domain
        from ..metrics.profiler import dispatch_context
        job = dispatch_context()[0]
        with self._lock:
            self.trips[site] = self.trips.get(site, 0) + 1
            if len(self.events) < 1024:
                self.events.append({
                    "timestamp": time.time(), "kind": "watchdog-stall",
                    "site": site, "scope": scope, "job": job,
                    "deadline_s": deadline})
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_watchdog_trip(site)
        # the post-mortem moment: the stall span lands in the flight
        # recorder's ring FIRST, then the dump snapshots the ring — the
        # dump's tail always contains the stall site that triggered it
        from ..metrics.tracing import TRACER, dump_flight_recorder
        (TRACER.span("watchdog", "Stall")
         .set_attribute("site", site)
         .set_attribute("scope", scope)
         .set_attribute("job", job)
         .set_attribute("deadline_s", deadline)
         .finish())
        dump_flight_recorder("stall", site=site, scope=scope, job=job,
                             deadline_s=deadline)


#: The process-global watchdog every wrapped site consults.
#: ``deploy_local`` / ``DistributedHost.deploy`` / bench configure it
#: from the job Configuration.
WATCHDOG = Watchdog()


def stall_bounded(site: str, fn: Callable, scope: Optional[str] = None,
                  deadline: Optional[float] = None,
                  retries: Optional[int] = None):
    """The shared idiom for watchdogging an IDEMPOTENT blocking region
    (uploads, materializations, checkpoint writes): visit ``site``'s
    fault rule (raising trips keep their transient-retry semantics; hang
    trips sleep on the supervised worker) and run ``fn`` under the
    site's deadline. A stall abandons the worker and retries in place up
    to ``watchdog.stall-retries`` times — retrying is safe precisely
    because the region is idempotent — then propagates ``StallError``
    into task failover. Compiled-segment dispatches use ``DeviceGuard``
    (which owns its own retry/degrade ladder) instead of this helper."""
    from .faults import FAULTS, fire_with_retries

    def _body():
        if FAULTS.enabled:
            fire_with_retries(site, scope=scope)
        return fn()

    max_retries = WATCHDOG.stall_retries if retries is None else retries
    attempt = 0
    while True:
        try:
            return WATCHDOG.run(site, _body, deadline=deadline, scope=scope)
        except StallError:
            if attempt >= max_retries:
                raise
            attempt += 1
            from ..metrics.device import DEVICE_STATS
            DEVICE_STATS.note_retry(scope or site)


# ---------------------------------------------------------------------------
# task-progress supervision
# ---------------------------------------------------------------------------

class TaskProgress:
    """Per-subtask progress epoch: the mailbox loop bumps it once per
    processed event/batch; age is wall-clock since the last bump. Cheap
    enough for the hot loop (one int increment + one clock read)."""

    __slots__ = ("epoch", "last_ts")

    def __init__(self):
        self.epoch = 0
        self.last_ts = time.time()

    def bump(self) -> None:
        self.epoch += 1
        self.last_ts = time.time()

    @property
    def age_ms(self) -> float:
        return (time.time() - self.last_ts) * 1000.0


class _ProgressRegistry:
    """Process-global task_id -> TaskProgress view, feeding the per-task
    ``last_progress_age_ms`` surface (REST /metrics/snapshot, bench)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: dict[str, TaskProgress] = {}

    def register(self, task_id: str, progress: TaskProgress) -> None:
        with self._lock:
            self._tasks[task_id] = progress

    def unregister(self, task_id: str) -> None:
        with self._lock:
            self._tasks.pop(task_id, None)

    def ages_ms(self) -> dict[str, float]:
        with self._lock:
            items = list(self._tasks.items())
        return {tid: round(p.age_ms, 1) for tid, p in items}


PROGRESS = _ProgressRegistry()


class TaskStallDetector:
    """Job-level stall detector: flags any subtask whose progress epoch
    has not advanced within ``task.stall-timeout`` while its input gates
    are non-empty, and routes it into the existing restart path by
    failing the task with a ``StallError`` (the local supervisor then
    performs a region restart or full restart-from-checkpoint; a
    distributed worker's failure report reaches the coordinator's
    redeploy logic — both exactly as for any other task failure)."""

    def __init__(self, job, stall_timeout: float,
                 interval: Optional[float] = None):
        self.job = job
        self.stall_timeout = stall_timeout
        self.interval = interval or max(stall_timeout / 4.0, 0.01)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_epoch: dict[str, tuple[int, float]] = {}
        self.detections = 0

    def start(self) -> "TaskStallDetector":
        if self.stall_timeout and self.stall_timeout > 0:
            self._thread = threading.Thread(
                target=self._loop, name="task-stall-detector", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if self.job._done.is_set():
                return
            self.scan()

    def scan(self) -> list[str]:
        """One detection pass; returns the task ids flagged (tests drive
        this directly for determinism)."""
        now = time.time()
        flagged = []
        for task_id, task in list(self.job.tasks.items()):
            progress = getattr(task, "progress", None)
            if progress is None or not task.is_alive:
                self._last_epoch.pop(task_id, None)
                continue
            epoch = progress.epoch
            seen, since = self._last_epoch.get(task_id, (None, now))
            if epoch != seen:
                self._last_epoch[task_id] = (epoch, now)
                continue
            if now - since < self.stall_timeout:
                continue
            if not task.input_pending():
                # no queued input: idle, not stalled (a source waiting on
                # data, a task whose upstream is quiet)
                continue
            self._last_epoch[task_id] = (epoch, now)  # re-arm, don't spam
            flagged.append(task_id)
            self._flag(task_id, task, now - since)
        return flagged

    def _flag(self, task_id: str, task, age_s: float) -> None:
        self.detections += 1
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_stall(task_id)
        err = StallError("task.progress", self.stall_timeout, scope=task_id)
        history = getattr(self.job, "failure_history", None)
        if history is not None:
            history.append({
                "timestamp": time.time(), "task": task_id,
                "kind": "stall-detected",
                "error": (f"no progress for {age_s:.3g}s with queued "
                          f"input (task.stall-timeout="
                          f"{self.stall_timeout:.3g}s)")})
        # cancel FIRST: when the wedged thread eventually unwinds it must
        # not report a second failure for the already-failed attempt
        task.cancel()
        self.job.task_failed(task_id, err)
