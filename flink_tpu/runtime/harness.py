"""Deterministic single-operator test harness.

Analog of the reference's operator harnesses
(flink-streaming-java test utils: AbstractStreamOperatorTestHarness.java:104,
OneInputStreamOperatorTestHarness, KeyedOneInputStreamOperatorTestHarness):
drive one operator (or a chain) with manual elements, watermarks, a manual
processing-time clock, and snapshot()/initialize_state() round-trips — no
cluster, no threads, fully deterministic. The workhorse for operator
semantics tests and for host/device parity checks.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..core.config import Configuration
from ..core.elements import Watermark
from ..core.records import MIN_TIMESTAMP, RecordBatch, Schema
from .operators.base import (
    CollectingOutput, OneInputOperator, OperatorChain, OperatorContext,
    TwoInputOperator,
)

__all__ = ["OneInputOperatorTestHarness", "TwoInputOperatorTestHarness"]


class OneInputOperatorTestHarness:
    def __init__(self, operator: OneInputOperator,
                 schema: Optional[Schema] = None,
                 config: Optional[Configuration] = None,
                 subtask_index: int = 0, parallelism: int = 1,
                 max_parallelism: int = 128, task_name: str = "harness"):
        self.operator = operator
        self.schema = schema
        self.output = CollectingOutput()
        self._now_ms = 0
        self.ctx = OperatorContext(
            task_name=task_name, subtask_index=subtask_index,
            parallelism=parallelism, max_parallelism=max_parallelism,
            config=config or Configuration(),
            processing_time=lambda: self._now_ms)
        # reuse chain wiring so side outputs & operator ids behave identically
        self.chain = OperatorChain([operator], self.ctx, self.output,
                                   side_outputs=None)
        self._opened = False

    # -- lifecycle ---------------------------------------------------------
    def open(self, keyed_snapshots: Optional[list] = None,
             operator_snapshot: Any = None) -> None:
        self.operator.initialize_state(keyed_snapshots or [], operator_snapshot)
        self.operator.open()
        self._opened = True

    def _ensure_open(self) -> None:
        if not self._opened:
            self.open()

    # -- drive -------------------------------------------------------------
    def process_element(self, value: Any, timestamp: int = MIN_TIMESTAMP) -> None:
        self.process_elements([value], [timestamp])

    def process_elements(self, values: Sequence[Any],
                         timestamps: Optional[Sequence[int]] = None) -> None:
        self._ensure_open()
        if self.schema is None:
            self.schema = Schema.infer(values[0])
        batch = RecordBatch.from_rows(self.schema, list(values),
                                      list(timestamps) if timestamps else None)
        self.operator.process_batch(batch)

    def process_batch(self, batch: RecordBatch) -> None:
        self._ensure_open()
        self.operator.process_batch(batch)

    def process_watermark(self, ts: int) -> None:
        self._ensure_open()
        self.operator.process_watermark(Watermark(int(ts)))

    def set_processing_time(self, now_ms: int) -> None:
        self._ensure_open()
        self._now_ms = int(now_ms)
        self.operator.advance_processing_time(self._now_ms)

    # -- snapshot/restore --------------------------------------------------
    def snapshot(self, checkpoint_id: int = 1) -> dict:
        return self.operator.snapshot_state(checkpoint_id)

    @staticmethod
    def restored(operator_factory, snapshot: dict, **kwargs
                 ) -> "OneInputOperatorTestHarness":
        """New harness whose operator starts from ``snapshot`` (the
        snapshot()/initializeState round-trip pattern)."""
        h = OneInputOperatorTestHarness(operator_factory(), **kwargs)
        keyed = [snapshot["keyed"]] if snapshot.get("keyed") else []
        h.open(keyed, snapshot.get("operator"))
        return h

    # -- inspect -----------------------------------------------------------
    def get_output(self) -> list:
        return self.output.rows()

    def get_watermarks(self) -> list[int]:
        return [w.timestamp for w in self.output.watermarks]

    def get_side_output(self, tag: str) -> list:
        return [r for b in self.output.side.get(tag, []) for r in b.iter_rows()]

    def clear_output(self) -> None:
        self.output.clear()

    def close(self) -> None:
        self.operator.finish()
        self.operator.close()


class TwoInputOperatorTestHarness:
    """Drive one TwoInputOperator deterministically (reference
    TwoInputStreamOperatorTestHarness): elements/watermarks per input,
    snapshot/restore round-trips."""

    def __init__(self, operator: TwoInputOperator,
                 schema1: Optional[Schema] = None,
                 schema2: Optional[Schema] = None,
                 config: Optional[Configuration] = None,
                 subtask_index: int = 0, parallelism: int = 1,
                 max_parallelism: int = 128, task_name: str = "harness2"):
        self.operator = operator
        self.schemas = [schema1, schema2]
        self.output = CollectingOutput()
        self._now_ms = 0
        self.ctx = OperatorContext(
            task_name=task_name, subtask_index=subtask_index,
            parallelism=parallelism, max_parallelism=max_parallelism,
            config=config or Configuration(),
            processing_time=lambda: self._now_ms)
        self.chain = OperatorChain([operator], self.ctx, self.output,
                                   side_outputs=None)
        self._opened = False

    def open(self, keyed_snapshots: Optional[list] = None,
             operator_snapshot: Any = None) -> None:
        self.operator.initialize_state(keyed_snapshots or [],
                                       operator_snapshot)
        self.operator.open()
        self._opened = True

    def _ensure_open(self) -> None:
        if not self._opened:
            self.open()

    def _process(self, input_index: int, values: Sequence[Any],
                 timestamps: Optional[Sequence[int]]) -> None:
        self._ensure_open()
        if self.schemas[input_index] is None:
            self.schemas[input_index] = Schema.infer(values[0])
        batch = RecordBatch.from_rows(
            self.schemas[input_index], list(values),
            list(timestamps) if timestamps else None)
        if input_index == 0:
            self.operator.process_batch1(batch)
        else:
            self.operator.process_batch2(batch)

    def process_element1(self, value: Any,
                         timestamp: int = MIN_TIMESTAMP) -> None:
        self._process(0, [value], [timestamp])

    def process_element2(self, value: Any,
                         timestamp: int = MIN_TIMESTAMP) -> None:
        self._process(1, [value], [timestamp])

    def process_elements1(self, values, timestamps=None) -> None:
        self._process(0, values, timestamps)

    def process_elements2(self, values, timestamps=None) -> None:
        self._process(1, values, timestamps)

    def process_watermark1(self, ts: int) -> None:
        self._ensure_open()
        self.operator.process_watermark_n(0, Watermark(int(ts)))

    def process_watermark2(self, ts: int) -> None:
        self._ensure_open()
        self.operator.process_watermark_n(1, Watermark(int(ts)))

    def snapshot(self, checkpoint_id: int = 1) -> dict:
        return self.operator.snapshot_state(checkpoint_id)

    @staticmethod
    def restored(operator_factory, snapshot: dict, **kwargs
                 ) -> "TwoInputOperatorTestHarness":
        h = TwoInputOperatorTestHarness(operator_factory(), **kwargs)
        keyed = [snapshot["keyed"]] if snapshot.get("keyed") else []
        h.open(keyed, snapshot.get("operator"))
        return h

    def get_output(self) -> list:
        return self.output.rows()

    def get_watermarks(self) -> list[int]:
        return [w.timestamp for w in self.output.watermarks]

    def clear_output(self) -> None:
        self.output.clear()

    def close(self) -> None:
        self.operator.finish()
        self.operator.close()
