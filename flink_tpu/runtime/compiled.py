"""Fused-chain lowering: the runtime half of the fusion certifier.

The graph-level analysis (graph/fusion.py) proves — statically, before
deployment — that a chained ``source-decode -> ... -> window-step``
prefix is lowerable to one XLA dispatch, and records the proof in the
job's ``FusionCertificate`` (``ChainReport.lowered_prefix``). This
module is what the proof buys at runtime: ``FusedChain`` composes the
device datagen decode and the window operator's ingest step
(``device_window._step_body``) under ONE donated ``jax.jit``, so a
certified micro-batch costs a single device dispatch instead of two
(decode program in the reader + step program in the operator), with
zero host work in between beyond the scalar bookkeeping both paths
already share.

Design points, all load-bearing:

- **Shape-keyed cache, iota as an input.** Programs are cached per
  batch length ``n``. The batch-length dependence is carried by a
  per-``n`` device ``iota = arange(n, int64)`` passed as an INPUT
  (not closed over), so every fused program's abstract signature
  contains an ``((n,), int64)`` leaf and two different batch lengths
  can never collide under the shape-only cache key. ``shape_key``
  reproduces ``analysis/jaxpr_rules._array_signature`` exactly —
  that is the JX603 contract (chain cache keys are shape-only, and
  key equality implies signature equality).

- **Audit before dispatch.** Both the decode prelude (scope
  ``chain.fused_prelude``) and the composed step (scope
  ``chain.fused_step``) register in the program-audit registry BEFORE
  the first dispatch: state buffers are donated, so their shapes are
  only inspectable while the arguments are still alive. The Tier-B
  rules audit these entries: JX601 proves the prelude scatter-free,
  JX602 proves donation survives the composition (input/output
  aliasing present in the lowered chain), JX603 proves the key
  discipline above.

- **Exact decode semantics.** The fused decode reproduces the
  reader's per-batch program bit for bit: same global index math
  ``(start + iota) * stride + subtask``, same per-field ``astype``,
  same monotonicity outputs (in-batch violation OR'd with the
  cross-batch tail comparison, plus the batch's last timestamp).
  The (viol, last) outputs are handed back to the reader through
  ``LazyDeviceBatch.deliver`` — fused and unfused runs are
  byte-identical, including the deferred contract check.

- **No note_build.** Like the reader's per-``n`` decode cache, fused
  chain compiles are not counted in ``DEVICE_STATS.compiles`` — the
  recompile budget tracks the instrumented program caches, and the
  bench acceptance gate (recompiles == 0 in the timed stage) holds
  for fused runs exactly as for unfused ones. Dispatches are counted
  (``chain_fused_dispatches_total``): exactly one per micro-batch is
  the observable the acceptance test asserts.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any

import numpy as np

from ..metrics.device import DEVICE_STATS, _record_program_audit
from ..metrics.profiler import DEVICE_LEDGER

__all__ = ["CHAIN_PRELUDE_SCOPE", "CHAIN_STEP_SCOPE", "shape_key",
           "FusedChain"]

# audit scopes — jaxpr_rules keys its chain rules off these exact names
CHAIN_PRELUDE_SCOPE = "chain.fused_prelude"
CHAIN_STEP_SCOPE = "chain.fused_step"


def shape_key(args: tuple, kwargs: dict | None = None) -> str:
    """Shape-only cache key over a call's arguments — the runtime twin
    of ``analysis/jaxpr_rules._array_signature`` (must stay
    representation-identical: JX603 checks ``build_key`` equality
    against that function's output over the audited abstract args)."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
    return repr(sig)


# Process-global chain program cache, mirroring the step program's
# instrumented_program_cache: a fused job pays the chain compile once
# per (source gen, schema, placement, fold signature, geometry, batch
# length) for the life of the process, not once per deployed operator —
# without this every env.execute() recompiles the chain and the fused
# path loses its dispatch savings to fixed compile cost. Keyed on the
# gen FUNCTION OBJECT (not its code) so two closures with different
# captured constants can never share a program.
# lint: guarded-by single-writer — mutated only via FusedChain.run on the task mailbox thread
_PROGRAM_CACHE: dict = {}
_MAX_PROGS = 64


class FusedChain:
    """Composed decode+step programs for one certified chain, one per
    batch length (the reader's power-of-two bucketing bounds the
    population exactly as it bounds its own ``_progs``). Programs live
    in the module-global ``_PROGRAM_CACHE`` keyed by everything the
    build closes over, so redeploys of the same pipeline reuse them."""

    def __init__(self, source, subtask: int, parallelism: int,
                 key_column: str, fold_sig: tuple, ring: int, pane: int,
                 offset: int, dirty_block: int):
        self._src = source
        self._subtask = int(subtask)
        self._parallelism = int(parallelism)
        self._key_column = key_column
        self._sig = tuple(fold_sig)
        self._ring = int(ring)
        self._pane = int(pane)
        self._offset = int(offset)
        self._dirty_block = int(dirty_block)
        src = self._src
        self._cache_key = (
            src._gen, tuple((f.name, str(f.dtype)) for f in src.schema.fields),
            src._ts_col, self._subtask, self._parallelism, key_column,
            self._sig, self._ring, self._pane, self._offset,
            self._dirty_block)

    # -- program construction ---------------------------------------------
    def _build(self, n: int) -> dict[str, Any]:
        import jax
        import jax.numpy as jnp

        from ..ops.hash_table import ensure_x64
        from .operators.device_window import _step_body

        ensure_x64()
        s = self._src
        stride, off = self._parallelism, self._subtask
        fields = s.schema.fields
        ts_col = s._ts_col
        sig = self._sig
        key_col = self._key_column
        step = _step_body(sig, self._ring, self._pane, self._offset,
                          self._dirty_block, 0)

        def decode(iota, start, prev_last):
            # identical integer math to _DeviceDataGenReader._program —
            # fused and unfused runs must be byte-identical
            idx = (start + iota) * stride + off
            cols = s._gen(idx)
            out = {f.name: jnp.asarray(cols[f.name]).astype(f.dtype)
                   for f in fields}
            ts = out[ts_col]
            viol = (jnp.any(ts[1:] < ts[:-1])
                    | (ts[0].astype(jnp.int64) < prev_last))
            return out, ts.astype(jnp.int64), viol, ts[-1].astype(jnp.int64)

        # the decode alone, registered under the prelude scope so JX601
        # can prove the fused prefix scatter-free in isolation
        prelude = jax.jit(decode)

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4, 5))
        def chain(iota, table, arrays, dropped, late, dirty, start,
                  prev_last, first_open):
            out, ts, viol, last = decode(iota, start, prev_last)
            cols = {f: out[f] for _k, _n, f in sig}
            table, arrays, dropped, late, dirty, _stage, _touch, token = \
                step(table, arrays, dropped, late, dirty, None, None,
                     out[key_col], ts, cols, None, jnp.int64(0),
                     first_open, n)
            return table, arrays, dropped, late, dirty, viol, last, token

        return {"chain": chain, "prelude": prelude,
                "iota": jnp.arange(n, dtype=jnp.int64), "registered": False}

    # -- dispatch ----------------------------------------------------------
    def run(self, n: int, start, prev_last, table, arrays, dropped, late,
            dirty, first_open):
        """One fused dispatch: decode batch [start, start+n) and fold it
        into the donated window state. Returns the step outputs plus the
        decode's (viol, last) for ``LazyDeviceBatch.deliver``."""
        key = self._cache_key + (n,)
        prog = _PROGRAM_CACHE.get(key)
        if prog is None:
            if len(_PROGRAM_CACHE) >= _MAX_PROGS:
                _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
            prog = self._build(n)
            _PROGRAM_CACHE[key] = prog
        args = (prog["iota"], table, arrays, dropped, late, dirty,
                np.int64(start), prev_last, np.int64(first_open))
        if not prog["registered"]:
            # before the dispatch: donation consumes the state buffers,
            # after which their shapes are gone
            prog["registered"] = True
            pargs = (prog["iota"], np.int64(start), prev_last)
            _record_program_audit(CHAIN_PRELUDE_SCOPE, prog["prelude"],
                                  pargs, {}, shape_key(pargs))
            _record_program_audit(CHAIN_STEP_SCOPE, prog["chain"],
                                  args, {}, shape_key(args))
            prog["sig"] = shape_key(args)
            # ledger marker for the prelude program: zero-duration by
            # design — its trace/compile cost is paid inside the first
            # fused-step dispatch, which is charged below
            DEVICE_LEDGER.record("chain.fused_prelude", 0.0,
                                 shape_sig=shape_key(pargs),
                                 kind="compile")
        timed = DEVICE_LEDGER.enabled
        t0 = time.perf_counter() if timed else 0.0
        out = prog["chain"](*args)
        if timed:
            # the first dispatch traces/lowers/compiles synchronously:
            # charge it as compile time, not a steady-state sample
            DEVICE_LEDGER.record(
                "chain.fused_step", (time.perf_counter() - t0) * 1e3,
                shape_sig=prog.get("sig", ""),
                kind="dispatch" if prog.get("compiled") else "compile")
        prog["compiled"] = True
        DEVICE_STATS.note_chain_dispatch()
        return out
