"""Deterministic fault injection + the device-path retry/degrade guard.

The chaos substrate for the whole stack (the injectable analog of the
reference's process-kill ITCases, SURVEY §5.3, generalized from "kill the
JVM" to "fail THIS site on THIS visit"): a process-wide registry of named
fault sites threaded through the device operators, the transfer points,
channels, the sink, checkpoint storage, and the cluster heartbeat. Every
site is seeded and schedulable through ``Configuration`` keys
(``faults.enabled`` / ``faults.seed`` / ``faults.spec``), so a chaos run
replays byte-identically: same seed + same spec + same visit order =>
the same trips, down to the visit number recorded in each event.

Sites (see docs/ROBUSTNESS.md for where each is threaded):

    device.compile    building a compiled program (XLA compile)
    device.execute    dispatching a compiled segment (step/fire/fold)
    transfer.h2d      host->device upload of a batch/column
    transfer.d2h      device->host materialization (fires, snapshots)
    channel.send      writing into a downstream channel
    channel.backpressure  drop-style: a put reports "queue full" once
    checkpoint.write  persisting a completed checkpoint
    checkpoint.load   reading a checkpoint back for restore
    checkpoint.corrupt   mutation-style: bit-flip a stored chunk file
    checkpoint.truncate  mutation-style: truncate a stored chunk file
    rpc.heartbeat     drop-style: a worker heartbeat frame is lost
    rpc.send          a worker<->coordinator control frame send
    sink.invoke       delivering a batch to a sink function/writer
    bench.probe       the bench backend-availability probe
    net.connect       establishing (or re-establishing) a data-plane
                      TCP connection — a trip is one failed attempt,
                      absorbed by the reconnect loop's deadline
    net.sever         drop-style: kill the established socket out from
                      under a data-plane send (simulated TCP RST)
    net.delay         drop-style: data-plane send latency — use !hang@MS
                      (a trip without the hang flag is a no-op)
    net.zombie        drop-style: suppress a worker's heartbeats AND its
                      control-reconnect reflex while tasks and data keep
                      flowing (the partitioned-but-alive split-brain)
    sched.admit       the per-job admission gate sources poll before
                      reading a micro-batch (cluster/isolation.py)
    sched.shed        drop-style: force the admission gate to shed the
                      next micro-batch to the dead-letter output even
                      without real overload
    coord.crash       drop-style: the LEADER coordinator crashes — drops
                      its server plus every worker control socket and
                      stops renewing its lease, so a standby can steal
                      leadership and take the running job over
    ha.lease          a leader-lease renew or steal attempt fails (or,
                      with !hang@MS, stalls — the GC-pause analog that
                      lets the lease expire under a live leader)
    aot.load          reading a persisted AOT executable artifact back
                      (warm-start scan); a !poison trip corrupt-mutates
                      the read bytes so digest verification — not luck —
                      must catch it (the checkpoint.corrupt analog)
    aot.store         persisting a freshly-compiled executable; a trip
                      skips persistence (compile-on-miss next process),
                      a !poison trip commits a corrupt-mutated artifact
                      for the verified load path to quarantine

Every rule also accepts a ``!hang@MS`` flag: the trip SLEEPS MS
milliseconds at the site instead of raising — the deterministic stand-in
for a wedged call, surfaced by the stall watchdog's per-site deadline
(runtime/watchdog.py) rather than by an exception.

A ``!job@NAME`` flag scopes a rule to one tenant: it only trips when the
thread-local dispatch context (metrics/profiler.py) attributes the visit
to job NAME, and it counts visits per ``site!job@NAME`` stream — the
multi-job isolation drill poisons or hangs job A's dispatches without
touching job B's. A site may carry several comma-separated rules (e.g.
one per job); unfiltered single-rule specs behave exactly as before.

``DeviceGuard`` is the reflex around every compiled-segment call:
transient failures retry with exponential backoff (reusing the
cluster/failover.py strategy math); persistent failures surface as
``DeviceSegmentError`` so the operator can evacuate state and degrade to
its CPU-fallback path, and data-poison faults skip retry entirely (the
same batch cannot stop being poisoned) so the operator quarantines the
batch to a dead-letter output instead of folding it into state.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = ["FAULT_SITES", "InjectedFault", "HangAbandoned",
           "DeviceSegmentError", "FaultInjector", "FAULTS",
           "fire_with_retries", "DeviceGuard"]

#: Every site the runtime threads. ``configure`` rejects unknown sites so a
#: typo in a chaos spec fails loudly instead of silently injecting nothing.
FAULT_SITES = (
    "device.compile", "device.execute",
    "transfer.h2d", "transfer.d2h",
    "channel.send", "channel.backpressure",
    "checkpoint.write", "checkpoint.load",
    "checkpoint.corrupt", "checkpoint.truncate",
    "rpc.heartbeat", "rpc.send", "sink.invoke",
    "tier.evict", "tier.prefetch",
    "bench.probe",
    "net.connect", "net.sever", "net.delay", "net.zombie",
    "sched.admit", "sched.shed",
    "coord.crash", "ha.lease",
    "aot.load", "aot.store",
)


class InjectedFault(RuntimeError):
    """Raised (or reported, for drop-style sites) by a tripped fault rule.
    ``hang_ms > 0`` marks a hang fault: the site SLEEPS instead of
    raising (the deterministic stand-in for a wedged device call — the
    stall watchdog's deadline, not this exception, is what surfaces)."""

    def __init__(self, site: str, visit: int, transient: bool = True,
                 poison: bool = False, hang_ms: int = 0):
        super().__init__(
            f"injected fault at {site} (visit {visit}, "
            f"{'transient' if transient else 'persistent'}"
            f"{', poison' if poison else ''}"
            f"{f', hang {hang_ms}ms' if hang_ms else ''})")
        self.site = site
        self.visit = visit
        self.transient = transient
        self.poison = poison
        self.hang_ms = hang_ms


class HangAbandoned(RuntimeError):
    """An injected hang outlived its watchdog deadline: the caller was
    already handed a StallError, so the abandoned worker unwinds through
    this WITHOUT executing the real operation (exactly-once: nothing the
    caller will retry can also run to completion here)."""


class DeviceSegmentError(RuntimeError):
    """A compiled-segment call failed beyond what retries can absorb.
    ``poison`` marks a data fault (quarantine the batch); otherwise the
    operator should degrade to its CPU-fallback path or fail over."""

    def __init__(self, scope: str, cause: BaseException,
                 poison: bool = False):
        super().__init__(f"device segment {scope!r} failed: {cause}")
        self.scope = scope
        self.cause = cause
        self.poison = poison


@dataclass
class FaultRule:
    """One parsed ``site=mode[!flags]`` entry of ``faults.spec``."""

    site: str
    mode: str            # "once" | "every" | "prob" | "always" | "off"
    at: int = 1          # once: trip ON this visit; every: period
    p: float = 0.0       # prob mode: per-visit trip probability
    transient: bool = True
    poison: bool = False
    hang_ms: int = 0     # >0: the trip SLEEPS this long instead of raising
    job: str = ""        # non-empty: only trips for this dispatch-context job

    @staticmethod
    def parse(entry: str) -> "FaultRule":
        entry = entry.strip()
        if "=" not in entry:
            raise ValueError(f"fault rule {entry!r}: expected 'site=mode'")
        site, _, mode = entry.partition("=")
        site = site.strip()
        if site not in FAULT_SITES:
            raise ValueError(f"unknown fault site {site!r} "
                             f"(known: {', '.join(FAULT_SITES)})")
        parts = mode.strip().split("!")
        mode, flags = parts[0].strip(), {f.strip() for f in parts[1:]}
        hang_ms = 0
        job = ""
        for f in list(flags):
            if f.startswith("hang@"):
                flags.discard(f)
                hang_ms = int(f[5:])
                if hang_ms < 1:
                    raise ValueError(
                        f"fault rule {entry!r}: hang@MS needs MS>=1")
            elif f.startswith("job@"):
                flags.discard(f)
                job = f[4:]
                if not job:
                    raise ValueError(
                        f"fault rule {entry!r}: job@NAME needs a name")
        bad = flags - {"persistent", "transient", "poison"}
        if bad:
            raise ValueError(f"fault rule {entry!r}: unknown flags {bad}")
        rule = FaultRule(site, "off",
                         transient="persistent" not in flags,
                         poison="poison" in flags, hang_ms=hang_ms,
                         job=job)
        if mode in ("off", ""):
            rule.mode = "off"
        elif mode == "always":
            rule.mode = "always"
        elif mode.startswith("once"):
            rule.mode = "once"
            rule.at = int(mode[5:]) if mode.startswith("once@") else 1
        elif mode.startswith("every@"):
            rule.mode = "every"
            rule.at = int(mode[6:])
            if rule.at < 1:
                raise ValueError(f"fault rule {entry!r}: every@N needs N>=1")
        elif mode.startswith("p"):
            rule.mode = "prob"
            rule.p = float(mode[1:])
            if not 0.0 <= rule.p <= 1.0:
                raise ValueError(f"fault rule {entry!r}: p out of [0,1]")
        else:
            raise ValueError(f"fault rule {entry!r}: unknown mode {mode!r}")
        return rule


class FaultInjector:
    """Process-wide registry of schedulable fault sites.

    Disabled (the default) every check is one attribute read. Enabled,
    each visit to a site increments a per-site counter under a lock and
    evaluates that site's rule; probability rules draw from a per-site
    ``random.Random((seed, site))`` stream, so determinism needs only the
    visit ORDER to be stable — which single-threaded mailbox loops give
    per subtask, and tests give globally.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.seed = 0
        self._rules: dict[str, list[FaultRule]] = {}
        self._visits: dict[str, int] = {}
        self._trips: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}
        self._fingerprint: Optional[tuple] = None
        self._suppress = 0  # >0: sites never trip (degrade/evacuate paths)
        self.events: list[dict] = []  # bounded trip log (site, visit)

    # -- configuration ---------------------------------------------------
    def configure(self, config) -> None:
        """Adopt ``faults.*`` keys from a job Configuration. Idempotent on
        an unchanged (enabled, seed, spec) fingerprint so failover
        redeploys of the SAME job keep their visit counters — a once@N
        fault must not re-arm on every restart attempt."""
        from ..core.config import FaultOptions

        enabled = bool(config.get(FaultOptions.ENABLED))
        seed = int(config.get(FaultOptions.SEED))
        spec = str(config.get(FaultOptions.SPEC) or "")
        fingerprint = (enabled, seed, spec)
        with self._lock:
            if fingerprint == self._fingerprint:
                return
        self.configure_spec(spec, seed=seed, enabled=enabled)
        with self._lock:
            self._fingerprint = fingerprint

    def configure_spec(self, spec: str, seed: int = 0,
                       enabled: bool = True) -> None:
        rules: dict[str, list[FaultRule]] = {}
        for entry in (spec or "").split(","):
            if not entry.strip():
                continue
            rule = FaultRule.parse(entry)
            rules.setdefault(rule.site, []).append(rule)
        with self._lock:
            self._rules = rules
            self.seed = seed
            self.enabled = enabled and bool(rules)
            self._visits.clear()
            self._trips.clear()
            self._rngs.clear()
            self.events.clear()
            self._fingerprint = None

    def reset(self) -> None:
        """Disarm and clear all schedules/counters (test isolation)."""
        with self._lock:
            self.enabled = False
            self._rules = {}
            self._visits.clear()
            self._trips.clear()
            self._rngs.clear()
            self.events.clear()
            self._fingerprint = None

    # -- suppression (degrade/evacuate paths must not re-trip) -----------
    class _Suppressed:
        def __init__(self, inj): self._inj = inj

        def __enter__(self):
            with self._inj._lock:
                self._inj._suppress += 1

        def __exit__(self, *exc):
            with self._inj._lock:
                self._inj._suppress -= 1
            return False

    def suppressed(self) -> "_Suppressed":
        """Context manager: sites never trip inside (the evacuation /
        fallback path of last resort must not be chaos-injected)."""
        return self._Suppressed(self)

    # -- the hot check ---------------------------------------------------
    def _trip(self, site: str) -> Optional[InjectedFault]:
        from ..metrics.profiler import dispatch_context

        ctx_job = dispatch_context()[0]
        with self._lock:
            if self._suppress:
                return None
            rules = self._rules.get(site)
            if not rules:
                return None
            visit = self._visits.get(site, 0) + 1
            self._visits[site] = visit
            # job-filtered rules count visits on their own per-tenant
            # stream (site!job@NAME) so every@N means "every Nth visit
            # BY that job"; at most one bump per stream per visit even
            # with several rules on it
            bumped: dict[str, int] = {site: visit}

            def stream_visit(key: str) -> int:
                if key not in bumped:
                    bumped[key] = self._visits.get(key, 0) + 1
                    # lint: lock-ok closure only called in the locked block
                    self._visits[key] = bumped[key]
                return bumped[key]

            hit_rule, hit_visit = None, visit
            for rule in rules:
                if rule.mode == "off":
                    continue
                if rule.job:
                    if ctx_job != rule.job:
                        continue
                    key = f"{site}!job@{rule.job}"
                    rvisit = stream_visit(key)
                else:
                    key, rvisit = site, visit
                if rule.mode == "once":
                    hit = rvisit == rule.at
                elif rule.mode == "every":
                    hit = rvisit % rule.at == 0
                elif rule.mode == "always":
                    hit = True
                else:  # prob
                    rng = self._rngs.get(key)
                    if rng is None:
                        rng = self._rngs[key] = random.Random(
                            f"{self.seed}:{key}")
                    hit = rng.random() < rule.p
                if hit:
                    hit_rule, hit_visit = rule, rvisit
                    break
            if hit_rule is None:
                return None
            rule = hit_rule
            self._trips[site] = self._trips.get(site, 0) + 1
            if len(self.events) < 4096:
                self.events.append({"site": site, "visit": hit_visit,
                                    "transient": rule.transient,
                                    "poison": rule.poison,
                                    "hang_ms": rule.hang_ms,
                                    "job": rule.job or ctx_job})
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_injected(site)
        return InjectedFault(site, hit_visit, transient=rule.transient,
                             poison=rule.poison, hang_ms=rule.hang_ms)

    def _hang(self, fault: InjectedFault) -> None:
        """Sleep out a hang trip OUTSIDE the injector lock, in small
        slices that watch the watchdog abandonment flag: once the caller
        gave up on this worker, the real operation behind the site must
        never run (exactly-once under stall-retry)."""
        from .watchdog import current_call_abandoned

        end = time.monotonic() + fault.hang_ms / 1000.0
        while True:
            if current_call_abandoned():
                raise HangAbandoned(
                    f"hang at {fault.site} abandoned by the watchdog")
            remaining = end - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.005))

    def fire(self, site: str) -> None:
        """Visit a raising site; raises InjectedFault when its rule trips.
        A hang trip sleeps instead (the stall, not an exception, IS the
        fault — the watchdog deadline is what surfaces it)."""
        if not self.enabled:
            return
        fault = self._trip(site)
        if fault is None:
            return
        if fault.hang_ms:
            self._hang(fault)
            return
        raise fault

    def check(self, site: str) -> bool:
        """Visit a drop-style site (lost heartbeat, full queue): returns
        True when the rule trips — the caller drops/declines instead of
        raising. Hang trips sleep and report not-tripped (the delay is
        the fault)."""
        if not self.enabled:
            return False
        fault = self._trip(site)
        if fault is None:
            return False
        if fault.hang_ms:
            self._hang(fault)
            return False
        return True

    # -- views -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "seed": self.seed,
                    "visits": dict(self._visits),
                    "trips": dict(self._trips)}


#: The process-global injector every site consults. ``deploy_local`` /
#: ``DistributedHost.deploy`` configure it from the job Configuration.
FAULTS = FaultInjector()


def fire_with_retries(site: str, scope: Optional[str] = None,
                      max_attempts: int = 5) -> int:
    """Visit a raising site with transient-retry semantics: a transient
    trip counts one retry (``DEVICE_STATS``) and re-visits; persistent or
    poison trips — and retry exhaustion — propagate. Returns the number of
    retries spent. The shared idiom for transfer/channel/sink sites whose
    'retry' IS simply attempting the operation again."""
    if not FAULTS.enabled:
        return 0
    from ..metrics.device import DEVICE_STATS
    for attempt in range(max_attempts + 1):
        try:
            FAULTS.fire(site)
            return attempt
        except InjectedFault as e:
            if not e.transient or e.poison or attempt >= max_attempts:
                raise
            DEVICE_STATS.note_retry(scope or site)
    return max_attempts  # pragma: no cover - loop always returns/raises


def _is_device_error(e: BaseException) -> bool:
    """Real accelerator-runtime failures (as opposed to programming
    errors, which must propagate untouched): anything out of the XLA
    runtime / PJRT client surfaces as XlaRuntimeError or JaxRuntimeError
    depending on the jaxlib vintage."""
    for t in type(e).__mro__:
        if t.__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
    return False


class DeviceGuard:
    """Retry/escalate wrapper around compiled-segment calls.

    * transient faults (injected-transient, or real XLA runtime errors)
      retry up to ``device.failover.max-retries`` with exponential
      backoff, counted in ``DEVICE_STATS`` (``device_retries_total``);
    * poison faults skip retry — re-running identical data cannot
      unpoison it — and surface as ``DeviceSegmentError(poison=True)``
      so the operator quarantines the batch;
    * persistent faults / exhausted retries surface as
      ``DeviceSegmentError`` for the operator's degradation ladder.

    ``active=False`` (set when an operator has degraded to its CPU
    fallback) turns the guard into a passthrough: the fallback path of
    last resort is never chaos-injected.
    """

    def __init__(self, scope: str, config=None):
        from ..cluster.failover import ExponentialDelayRestartStrategy
        from ..core.config import FaultOptions

        self.scope = scope
        self.active = True
        if config is not None:
            self.max_retries = int(config.get(FaultOptions.DEVICE_MAX_RETRIES))
            initial = float(config.get(FaultOptions.DEVICE_RETRY_BACKOFF))
            maximum = float(config.get(
                FaultOptions.DEVICE_RETRY_BACKOFF_MAX))
        else:
            self.max_retries, initial, maximum = 3, 0.005, 0.25
        # reuse the failover escalation math: consecutive failures back off
        # exponentially, a healthy call resets the ladder
        self._strategy = ExponentialDelayRestartStrategy(
            initial=initial, maximum=maximum, reset_after=60.0)
        self.retries = 0      # per-guard observability (bench/tests)
        self.failures = 0
        self.stalls = 0       # watchdog deadline expiries seen here

    @staticmethod
    def _note_breaker(success: bool) -> None:
        """Feed the owning job's circuit breaker (cluster/isolation.py):
        a surfaced DeviceSegmentError counts one failure toward tripping
        it open, a healthy guarded call resets the ladder. No-op unless
        isolation is enabled."""
        from ..cluster.isolation import ISOLATION
        if not ISOLATION.enabled:
            return
        from ..metrics.profiler import dispatch_context
        job = dispatch_context()[0]
        if success:
            ISOLATION.note_success(job)
        else:
            ISOLATION.note_failure(job)

    def _sites_ok(self, sites: tuple) -> None:
        for s in sites:
            FAULTS.fire(s)

    def run(self, fn: Callable, sites: tuple = ("device.execute",)):
        """Call ``fn`` (which performs the guarded upload+dispatch) after
        visiting ``sites``, the whole attempt deadline-bounded by the
        stall watchdog (site ``device.execute``). Retries transient
        failures AND stalls; raises DeviceSegmentError beyond that — so
        repeated stalls at one segment walk the same degradation ladder
        as repeated failures (evacuate + CPU-fallback pin)."""
        if not self.active:
            return fn()
        from ..metrics.tracing import TRACER
        from .watchdog import WATCHDOG, StallError

        def attempt_call():
            self._sites_ok(sites)
            return fn()

        attempt = 0
        while True:
            try:
                with (TRACER.span("device", "Execute")
                      .set_attribute("scope", self.scope)
                      .set_attribute("attempt", attempt)):
                    out = WATCHDOG.run("device.execute", attempt_call,
                                       scope=self.scope)
                if attempt:
                    self._strategy.notify_recovered()
                self._note_breaker(success=True)
                return out
            except StallError as e:
                # a stall is transient first: the abandoned worker never
                # ran the real dispatch (hang sleeps check abandonment),
                # so re-running it cannot double-fold
                self.stalls += 1
                err, retryable = e, True
            except InjectedFault as e:
                if e.poison:
                    self.failures += 1
                    self._note_breaker(success=False)
                    raise DeviceSegmentError(self.scope, e, poison=True) \
                        from e
                err, retryable = e, e.transient
            except Exception as e:  # noqa: BLE001 - classify, re-raise rest
                if not _is_device_error(e):
                    raise
                err, retryable = e, True
            if not retryable or attempt >= self.max_retries:
                self.failures += 1
                self._note_breaker(success=False)
                raise DeviceSegmentError(self.scope, err) from err
            attempt += 1
            self.retries += 1
            from ..metrics.device import DEVICE_STATS
            DEVICE_STATS.note_retry(self.scope)
            self._strategy.notify_failure()
            time.sleep(self._strategy.backoff_seconds())
