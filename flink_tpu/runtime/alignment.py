"""Watermark alignment across sources (reference SourceCoordinator.java:92
announceCombinedWatermark + WatermarkAlignmentParams/WatermarkAlignmentEvent).

Sources in the same alignment group must not run ahead of the group's
slowest source by more than ``max_drift``: each source periodically reports
its current watermark, the coordinator combines them into a group minimum,
and a source whose watermark exceeds ``min + max_drift`` pauses reading
until the group catches up. This caps cross-source event-time skew — the
amount of out-of-order state (open windows, join buffers) a downstream
keyed operator must hold, which on the TPU backend directly bounds the open
pane span the accumulator ring must cover.

In-process jobs share one coordinator per job. In SPMD distributed jobs each
host aggregates its local sources and ships group minima with its heartbeat;
the cluster coordinator combines them and broadcasts the global minima back
(cluster/distributed.py), so alignment spans hosts exactly like the
reference's operator-coordinator round trip.
"""

from __future__ import annotations

import threading
from typing import Optional

MAX_WATERMARK = (1 << 63) - 1

__all__ = ["WatermarkAlignmentCoordinator", "MAX_WATERMARK"]


class WatermarkAlignmentCoordinator:
    """Tracks per-(group, source) watermarks; computes the max allowed
    watermark per group. Idle/finished sources report MAX_WATERMARK which
    excludes them from the minimum (reference WatermarksWithIdleness +
    SourceCoordinator: idle subtasks don't hold the group back)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reported: dict[str, dict[str, int]] = {}   # group -> task -> wm
        self._drift: dict[str, int] = {}                 # group -> max drift
        # global minima pushed from the cluster coordinator (distributed
        # mode); combined with local reports via min()
        self._remote_min: dict[str, int] = {}

    def report(self, group: str, task_id: str, watermark: int,
               max_drift_ms: int) -> int:
        """Record ``task_id``'s watermark; returns the group's current max
        allowed watermark (min + drift)."""
        with self._lock:
            self._reported.setdefault(group, {})[task_id] = watermark
            self._drift[group] = max_drift_ms
            return self._max_allowed_locked(group)

    def unregister(self, group: str, task_id: str) -> None:
        """A finished source must not hold the group back forever."""
        with self._lock:
            self._reported.get(group, {}).pop(task_id, None)

    def group_min(self, group: str) -> int:
        """Minimum over this process's live reports (what a distributed
        host ships with its heartbeat)."""
        with self._lock:
            wms = [w for w in self._reported.get(group, {}).values()]
            return min(wms) if wms else MAX_WATERMARK

    def local_minima(self) -> dict[str, int]:
        with self._lock:
            return {g: (min(t.values()) if t else MAX_WATERMARK)
                    for g, t in self._reported.items()}

    def set_remote_minima(self, minima: dict[str, int]) -> None:
        """Install the cluster-combined minima (distributed broadcast).
        Replaces the previous view: a group whose remote sources all
        finished drops out and stops constraining local sources."""
        with self._lock:
            self._remote_min = dict(minima)

    def max_allowed(self, group: str) -> int:
        with self._lock:
            return self._max_allowed_locked(group)

    def _max_allowed_locked(self, group: str) -> int:
        wms = list(self._reported.get(group, {}).values())
        lo = min(wms) if wms else MAX_WATERMARK
        remote = self._remote_min.get(group)
        if remote is not None:
            lo = min(lo, remote)
        if lo >= MAX_WATERMARK:
            return MAX_WATERMARK
        return lo + self._drift.get(group, 0)
