"""Persistent verified AOT executable cache: compile-storm-free recovery.

Coordinator failover, live rescale, and plain process restarts all
restore *state* quickly, but a cold worker still pays full XLA
compilation for every program before it serves its first batch — recovery
time is dominated by an unbounded compile storm. Compiled executables are
state too: JAX's AOT path (``fn.lower(...).compile()`` +
``jax.experimental.serialize_executable``) lets us checkpoint them the
same way checkpoint storage persists key-group chunks.

Artifact contract (the PR-4 manifest machinery applied per artifact):

* one file per ``(scope, build-key, call-signature)`` — content-addressed
  name ``blake2b16(scope, build_key, call_sig, fingerprint).aotx``;
* a JSON header line (format tag, scope, build key, call signature,
  environment fingerprint, payload size + blake2b digest) followed by the
  pickled ``serialize_executable.serialize`` tuple;
* committed write-tmp/fsync/rename; a digest/size/format mismatch raises
  the same typed :class:`CorruptArtifactError` the checkpoint verifier
  uses, and the artifact is quarantined as ``<name>.corrupt``;
* the environment fingerprint (jax/jaxlib version, backend platform,
  device kind, x64 flag) discriminates artifacts so a stale executable is
  never deserialized onto the wrong target — skew is a cache miss, never
  an error.

Degradation ladder: every failure on this path — missing capability
(older jaxlib without ``serialize_executable``), corrupt or truncated or
version-skewed artifacts, injected ``aot.load`` / ``aot.store`` faults,
a stalled ``aot.warmup`` scan — degrades to live compilation. The cache
can only ever make a process faster, never fail a job.

Warm start: every cold-process path (``deploy_local``,
``DistributedHost`` deploy, rescale-up replicas, post-failover
successors) calls :meth:`AotRuntime.warmup`, which pre-deserializes every
fingerprint-matching artifact under a watchdog-bounded ``aot.warmup``
deadline before the first batch. A warmed program's builder skips the
compile counters entirely (``recompiles == 0`` is the contract the
failover × warm-start drills assert).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Optional

from ..checkpoint.storage import (CorruptArtifactError, _fsync_write,
                                  _payload_digest)

__all__ = ["AotRuntime", "AOT", "AOT_FORMAT", "environment_fingerprint",
           "verify_aot_cache"]

#: Format tag every artifact header carries; bumped on layout changes so
#: an old cache directory reads as all-skew (miss), never as garbage.
AOT_FORMAT = "flink-tpu-aot-v1"

_SUFFIX = ".aotx"
_EVENT_LIMIT = 512


def _serialization_module():
    """Capability probe: the AOT serialize/deserialize entry points, or
    None on older jaxlib vintages (callers downgrade to compile-on-miss)."""
    try:
        from jax.experimental import serialize_executable as mod
    except Exception:
        return None
    if not (hasattr(mod, "serialize") and hasattr(mod, "deserialize_and_load")):
        return None
    return mod


def environment_fingerprint() -> list:
    """Backend/version discriminator baked into every artifact: a stale
    executable must never load onto the wrong target, so fingerprint
    mismatch is treated as a plain cache miss."""
    import jax
    try:
        jaxlib_version = str(jax.lib.__version__)
    except Exception:
        jaxlib_version = "unknown"
    try:
        dev = jax.devices()[0]
        platform = str(getattr(dev, "platform", "unknown"))
        device_kind = str(getattr(dev, "device_kind", platform))
    except Exception:
        platform = device_kind = "unknown"
    x64 = bool(getattr(jax.config, "jax_enable_x64", False))
    return [AOT_FORMAT, str(jax.__version__), jaxlib_version, platform,
            device_kind, x64]


def _artifact_name(scope: str, build_key: str, call_sig: str,
                   fingerprint: list) -> str:
    ident = repr((scope, build_key, call_sig, tuple(fingerprint)))
    return hashlib.blake2b(ident.encode(), digest_size=16).hexdigest() + _SUFFIX


def _parse_artifact(raw: bytes, path: str) -> tuple:
    """Split + verify one artifact's header/payload; raises
    CorruptArtifactError on any integrity problem (truncation, digest
    mismatch, undecodable header, wrong format tag)."""
    nl = raw.find(b"\n")
    if nl < 0:
        raise CorruptArtifactError(f"AOT artifact {path}: no header line")
    try:
        header = json.loads(raw[:nl].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CorruptArtifactError(
            f"AOT artifact {path}: undecodable header ({e})") from e
    if not isinstance(header, dict) or header.get("format") != AOT_FORMAT:
        raise CorruptArtifactError(
            f"AOT artifact {path}: format "
            f"{header.get('format') if isinstance(header, dict) else header!r}"
            f" != {AOT_FORMAT}")
    payload = raw[nl + 1:]
    if len(payload) != header.get("payload_size"):
        raise CorruptArtifactError(
            f"AOT artifact {path}: payload truncated "
            f"({len(payload)} != {header.get('payload_size')} bytes)")
    if _payload_digest(payload) != header.get("payload_digest"):
        raise CorruptArtifactError(
            f"AOT artifact {path}: payload digest mismatch")
    return header, payload


class AotRuntime:
    """Process-global AOT executable cache (the ``FAULTS``/``WATCHDOG``
    singleton pattern): deploy paths ``configure()`` it from the job
    Configuration and ``warmup()`` it before the first batch; the
    instrumented program cache consults it at build and dispatch time."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.directory = ""
        self.in_memory_max_programs = 0
        #: (scope, build_key, call_sig) -> deserialized executable
        self._loaded: dict[tuple, Any] = {}
        #: (scope, build_key) prefixes with >=1 warm executable — the
        #: build-time "skip the compile counters" check
        self._programs: set[tuple] = set()
        self.warmed = False
        self._capable = False
        self._capability_warned = False
        #: bounded event log merged into REST /jobs/<name>/exceptions
        self.events: list[dict] = []

    # -- configuration ---------------------------------------------------
    def configure(self, config) -> None:
        """Adopt ``aot.*`` keys from a job Configuration. Marks the
        process cold-start clock (``cold_start_ms``) the first time an
        enabled cache is configured."""
        from ..core.config import AotOptions

        enabled = bool(config.get(AotOptions.ENABLED))
        directory = str(config.get(AotOptions.DIR) or "")
        cap = int(config.get(AotOptions.IN_MEMORY_MAX_PROGRAMS))
        capable = _serialization_module() is not None
        with self._lock:
            changed = directory != self.directory
            self.enabled = enabled and bool(directory)
            self.directory = directory
            self.in_memory_max_programs = max(cap, 0)
            self._capable = capable
            if changed:
                self._loaded.clear()
                self._programs.clear()
                self.warmed = False
        if self.enabled:
            try:
                os.makedirs(directory, exist_ok=True)
            except OSError as e:
                self._event("aot-dir-unusable", error=str(e))
                with self._lock:
                    self.enabled = False
                return
            if not capable:
                self._warn_capability()
            # The cache serves device-state programs exclusively, and that
            # path runs under x64 (hash_table.ensure_x64 flips it lazily at
            # first use). Adopt the regime now, BEFORE the warmup scan
            # fingerprints the process — otherwise artifacts stored after
            # the state path ran (x64 on) read as version skew to a warmup
            # that scanned before it (x64 still off).
            from ..ops.hash_table import ensure_x64
            ensure_x64()
            from ..metrics.device import DEVICE_STATS
            DEVICE_STATS.mark_cold_start()

    def reset(self) -> None:
        """Disarm and clear all warm state (test isolation)."""
        with self._lock:
            self.enabled = False
            self.directory = ""
            self.in_memory_max_programs = 0
            self._loaded.clear()
            self._programs.clear()
            self.warmed = False
            self._capable = False
            self._capability_warned = False
            self.events.clear()

    # -- capability ------------------------------------------------------
    @property
    def capable(self) -> bool:
        return self._capable and _serialization_module() is not None

    def dispatch_active(self) -> bool:
        """True when dispatches should consult the persistent cache:
        enabled, a directory is set, and the jaxlib vintage can
        (de)serialize executables. One attribute read when disabled."""
        return self.enabled and self._capable

    def _warn_capability(self) -> None:
        """A single warning event when serialization is unavailable —
        the cache silently downgrades to compile-on-miss, never raises."""
        with self._lock:
            if self._capability_warned:
                return
            self._capability_warned = True
        self._event(
            "aot-capability-missing",
            detail="jax.experimental.serialize_executable unavailable on "
                   "this jax/jaxlib; AOT cache downgraded to "
                   "compile-on-miss (no executables persisted or loaded)")

    # -- events ----------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        with self._lock:
            if len(self.events) < _EVENT_LIMIT:
                self.events.append(
                    {"timestamp": time.time(), "kind": kind, **fields})

    # -- lookups ---------------------------------------------------------
    def has_program(self, scope: str, build_key: str) -> bool:
        """True when warmup pre-loaded at least one executable for this
        (scope, build-key) — the builder then skips the compile counters,
        the recompile-attribution ledger, and the device.compile site."""
        if not (self.enabled and self.warmed):
            return False
        with self._lock:
            return (scope, build_key) in self._programs

    def lookup(self, scope: str, build_key: str, call_sig: str):
        """A warm executable for this exact dispatch signature, or None.
        Counts one aot hit/miss per (program, signature)."""
        with self._lock:
            compiled = self._loaded.get((scope, build_key, call_sig))
        from ..metrics.device import DEVICE_STATS
        if compiled is not None:
            DEVICE_STATS.note_aot_hit(scope)
        else:
            DEVICE_STATS.note_aot_miss(scope)
        return compiled

    def note_dispatch_fallback(self, scope: str, error: BaseException) -> None:
        """A loaded executable failed to dispatch — degrade to the live
        jit path for that signature, counted and surfaced."""
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_aot_fallback(scope)
        self._event("aot-dispatch-fallback", scope=scope, error=str(error))

    @staticmethod
    def call_signature(args: tuple, kwargs: dict) -> Optional[str]:
        """Shape/dtype signature of one dispatch's arguments (the key
        discriminating compiled specializations under one build key).
        None when a leaf is neither an array nor a plain static value —
        such dispatches just use the live jit path."""
        try:
            import jax
            leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        except Exception:
            return None
        sig = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is not None and dtype is not None:
                sig.append((tuple(shape), str(dtype)))
            elif isinstance(leaf, (bool, int, float, str, bytes, type(None))):
                sig.append(("static", repr(leaf)))
            else:
                return None
        return repr((str(treedef), sig))

    # -- store -----------------------------------------------------------
    def store(self, scope: str, build_key: str, call_sig: str,
              compiled) -> bool:
        """Persist one freshly-compiled executable. Every failure —
        serialization, an injected ``aot.store`` trip, an unwritable
        directory — skips persistence and returns False; the in-process
        program keeps serving. A poison trip commits a corrupt-mutated
        payload (the ``checkpoint.corrupt`` analog) that the verified
        load path must catch."""
        if not self.dispatch_active():
            return False
        mod = _serialization_module()
        if mod is None:
            self._warn_capability()
            return False
        try:
            payload = pickle.dumps(mod.serialize(compiled))
        except Exception as e:  # noqa: BLE001 - any failure degrades
            self._event("aot-serialize-failed", scope=scope, error=str(e))
            return False
        poison = False
        from .faults import InjectedFault, fire_with_retries
        try:
            fire_with_retries("aot.store", scope=scope)
        except InjectedFault as e:
            if not e.poison:
                self._event("aot-store-failed", scope=scope, error=str(e))
                return False
            poison = True
        fingerprint = environment_fingerprint()
        header = json.dumps({
            "format": AOT_FORMAT, "scope": scope, "build_key": build_key,
            "call_sig": call_sig, "fingerprint": fingerprint,
            "payload_size": len(payload),
            "payload_digest": _payload_digest(payload),
        }, sort_keys=True).encode()
        if poison and payload:
            # digest was taken over the clean payload, so the committed
            # artifact is corrupt-on-disk: the load path MUST detect it
            mutated = bytearray(payload)
            mutated[len(mutated) // 2] ^= 0x40
            payload = bytes(mutated)
        name = _artifact_name(scope, build_key, call_sig, fingerprint)
        try:
            _fsync_write(os.path.join(self.directory, name),
                         header + b"\n" + payload)
        except OSError as e:
            self._event("aot-store-failed", scope=scope, error=str(e))
            return False
        with self._lock:
            # keep the (clean, in-memory) executable registered so an
            # LRU-evicted builder-cache entry rebuilt later finds it warm
            # — eviction + AOT reload is never a recompile
            self._loaded[(scope, build_key, call_sig)] = compiled
            self._programs.add((scope, build_key))
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_aot_store(scope)
        return True

    # -- warm start ------------------------------------------------------
    def warmup(self) -> int:
        """Pre-deserialize every fingerprint-matching artifact in the
        cache directory under the watchdog-bounded ``aot.warmup``
        deadline. Returns the number of executables loaded; degrades on
        stall/corruption/capability gaps (partial loads stay usable),
        never raises."""
        if not (self.enabled and self.directory):
            return 0
        if _serialization_module() is None:
            self._warn_capability()
            with self._lock:
                self.warmed = True
            return 0
        from .watchdog import WATCHDOG, StallError
        loaded = 0
        try:
            loaded = WATCHDOG.run("aot.warmup", self._warmup_scan,
                                  scope="aot")
        except StallError as e:
            # the scan registers executables as it goes, so whatever it
            # loaded before the deadline still serves; the rest miss
            self._event("aot-warmup-stalled", error=str(e))
            with self._lock:
                loaded = len(self._loaded)
        with self._lock:
            self.warmed = True
        return loaded

    def _warmup_scan(self) -> int:
        mod = _serialization_module()
        fingerprint = environment_fingerprint()
        loaded = 0
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return 0
        from .faults import InjectedFault
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                header, payload = self._read_artifact(path)
            except InjectedFault as e:
                from ..metrics.device import DEVICE_STATS
                DEVICE_STATS.note_aot_fallback("warmup")
                self._event("aot-load-failed", artifact=name, error=str(e))
                continue
            except CorruptArtifactError as e:
                self._quarantine(path, str(e))
                continue
            except OSError as e:
                self._event("aot-load-failed", artifact=name, error=str(e))
                continue
            if header.get("fingerprint") != fingerprint:
                # version/backend skew: a miss, never an error
                self._event("aot-version-skew", artifact=name,
                            artifact_fingerprint=header.get("fingerprint"),
                            process_fingerprint=fingerprint)
                continue
            key = (header["scope"], header["build_key"], header["call_sig"])
            with self._lock:
                if key in self._loaded:
                    continue  # re-scan (rescale/takeover): already warm
            try:
                compiled = mod.deserialize_and_load(*pickle.loads(payload))
            except Exception as e:  # noqa: BLE001 - artifact unusable
                self._quarantine(path, f"undeserializable payload: {e}")
                continue
            with self._lock:
                self._loaded[key] = compiled
                self._programs.add(key[:2])
            loaded += 1
        return loaded

    def _read_artifact(self, path: str) -> tuple:
        """Read + verify one artifact under the ``aot.load`` fault site.
        A poison trip mutates the payload before verification (the
        corrupt-mutation flavor), so the digest check — not luck — is
        what catches it."""
        from .faults import InjectedFault, fire_with_retries
        poison = False
        try:
            fire_with_retries("aot.load", scope="aot")
        except InjectedFault as e:
            if not e.poison:
                raise
            poison = True
        with open(path, "rb") as f:
            raw = f.read()
        if poison and raw:
            mutated = bytearray(raw)
            mutated[len(mutated) // 2] ^= 0x40
            raw = bytes(mutated)
        return _parse_artifact(raw, path)

    def _quarantine(self, path: str, reason: str) -> None:
        """Corrupt artifact: rename to ``<name>.corrupt`` so it never
        sits in the warmup scan again, count + flight-record it."""
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass
        self._event("aot-corrupt-artifact",
                    artifact=os.path.basename(path), error=reason)
        from ..metrics.device import DEVICE_STATS
        DEVICE_STATS.note_verify_failure("aot.artifact")

    # -- views -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "directory": self.directory,
                    "capable": self._capable, "warmed": self.warmed,
                    "loaded_executables": len(self._loaded),
                    "loaded_programs": len(self._programs)}


#: The process-global AOT cache every instrumented program consults.
#: ``deploy_local`` / ``DistributedHost.deploy`` / bench configure and
#: warm it from the job Configuration.
AOT = AotRuntime()


def verify_aot_cache(directory: str) -> list:
    """Offline artifact verification for the CLI: ``(artifact, status,
    detail)`` rows — OK (header + digest verify), CORRUPT (any integrity
    failure), QUARANTINED (``*.corrupt`` left by a prior run)."""
    rows = []
    try:
        names = sorted(os.listdir(directory))
    except OSError as e:
        return [(directory, "CORRUPT", f"unreadable directory: {e}")]
    for name in names:
        path = os.path.join(directory, name)
        if name.endswith(".corrupt"):
            rows.append((name, "QUARANTINED", "quarantined by a prior run"))
            continue
        if not name.endswith(_SUFFIX):
            continue
        try:
            with open(path, "rb") as f:
                raw = f.read()
            header, _payload = _parse_artifact(raw, path)
        except (CorruptArtifactError, OSError) as e:
            rows.append((name, "CORRUPT", str(e)))
            continue
        fp = header.get("fingerprint") or []
        rows.append((name, "OK",
                     f"scope={header.get('scope')} "
                     f"jax={fp[1] if len(fp) > 1 else '?'}"))
    return rows
