"""Runtime: tasks, channels, operators, timers, harness (SURVEY.md §2.5/L4)."""

from .faults import (  # noqa: F401
    DeviceGuard, DeviceSegmentError, FAULTS, FaultInjector, InjectedFault,
)
from .harness import OneInputOperatorTestHarness  # noqa: F401
from .timers import InternalTimerService, Timer  # noqa: F401
from .watchdog import (  # noqa: F401
    StallError, TaskStallDetector, WATCHDOG, Watchdog, stall_bounded,
)
