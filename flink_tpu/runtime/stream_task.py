"""Stream tasks: the per-subtask execution loop.

Analog of the reference's StreamTask family
(flink-streaming-java runtime/tasks/: StreamTask.java:192 invoke():821 /
processInput:588, SourceStreamTask, OneInputStreamTask) and its mailbox
(mailbox/MailboxProcessor.java:67): a single thread per subtask alternates
between the default action (process one input event) and 'mails' (checkpoint
triggers, coordinator commands) — operators never see concurrency.

Differences from the reference, by design:
* input is batch-granular; micro-batch coalescing happens at sources;
* backpressure is bounded-queue blocking (credit analog);
* processing time advances from the loop between events, keeping tests
  deterministic (a harness can inject a manual clock via OperatorContext).
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.config import (
    CheckpointingOptions, Configuration, MetricOptions, PipelineOptions,
)
from ..core.elements import (
    MAX_WATERMARK, CheckpointBarrier, EndOfInput, LatencyMarker, Watermark,
    WatermarkStatus,
)
from ..core.records import MIN_TIMESTAMP, RecordBatch
from ..core.watermarks import WatermarkStrategy
from ..connectors.core import SinkWriter, Source, SourceReader
from ..metrics.tracing import TRACER, TraceContext, now_ms
from ..state.backend import OperatorStateBackend
from .channels import GateEvent, InputGate
from .operators.base import OperatorChain, OperatorContext, Output
from .writer import RecordWriter

__all__ = ["StreamTask", "SourceStreamTask", "OneInputStreamTask",
           "TwoInputStreamTask", "TaskReporter", "TaskIOTimers"]


class TaskIOTimers:
    """Cumulative busy/idle/backpressured wall-clock for one subtask's
    mailbox loop (reference TaskIOMetricGroup's busyTimeMsPerSecond /
    idleTimeMsPerSecond / backPressuredTimeMsPerSecond TimerGauges, run-
    cumulative here instead of last-second-windowed). ``busy_s`` is raw
    processing time and INCLUDES time blocked inside emits; the writer
    accounts that blocked time into ``backpressured_s`` separately, so
    the derived ratios subtract it — busy means 'making progress'."""

    __slots__ = ("busy_s", "idle_s", "backpressured_s",
                 "_started_at", "_ended_at")

    def __init__(self):
        self.busy_s = 0.0
        self.idle_s = 0.0
        self.backpressured_s = 0.0
        self._started_at: Optional[float] = None
        self._ended_at: Optional[float] = None

    def start(self) -> None:
        if self._started_at is None:
            self._started_at = time.time()

    def stop(self) -> None:
        # freeze elapsed at task exit so post-run gauge reads are stable
        if self._ended_at is None:
            self._ended_at = time.time()

    @property
    def elapsed_s(self) -> float:
        if self._started_at is None:
            return 0.0
        return max((self._ended_at or time.time()) - self._started_at,
                   1e-9)

    @property
    def busy_ratio(self) -> float:
        return min(1.0, max(0.0, self.busy_s - self.backpressured_s)
                   / self.elapsed_s)

    @property
    def busy_ms_per_s(self) -> float:
        return self.busy_ratio * 1000.0

    @property
    def idle_ms_per_s(self) -> float:
        return min(1.0, self.idle_s / self.elapsed_s) * 1000.0

    @property
    def backpressured_ms_per_s(self) -> float:
        return min(1.0, self.backpressured_s / self.elapsed_s) * 1000.0


class TaskReporter:
    """Callbacks from tasks to the control plane (analog of the
    TaskExecutor->JobMaster RPC surface)."""

    def acknowledge_checkpoint(self, task_id: str, checkpoint_id: int,
                               snapshot: dict) -> None:
        pass

    def declined_checkpoint(self, task_id: str, checkpoint_id: int,
                            reason: str) -> None:
        pass

    def task_finished(self, task_id: str) -> None:
        pass

    def task_failed(self, task_id: str, error: BaseException) -> None:
        pass


class _WriterFanout(Output):
    """Chain tail output -> this task's RecordWriters. Control elements
    (watermarks, latency markers) broadcast over side-output writers too —
    downstream of a side edge still needs event time to advance."""

    def __init__(self, writers: list[RecordWriter], metrics=None,
                 side_writers: Optional[dict[str, list[RecordWriter]]] = None):
        self._writers = writers
        self._metrics = metrics
        self._side = side_writers or {}

    def _all_writers(self):
        yield from self._writers
        for ws in self._side.values():
            yield from ws

    def emit(self, batch: RecordBatch) -> None:
        if self._metrics is not None:
            self._metrics.records_out.inc(batch.n)
        for w in self._writers:
            w.emit(batch)

    def emit_watermark(self, watermark: Watermark) -> None:
        for w in self._all_writers():
            w.emit_watermark(watermark)

    def emit_latency_marker(self, marker: LatencyMarker) -> None:
        for w in self._all_writers():
            w.broadcast(marker)

    def emit_side(self, tag: str, batch: RecordBatch) -> None:
        for w in self._side.get(tag, ()):
            w.emit(batch)


def _barrier_spans(task_id: str, barrier: CheckpointBarrier,
                   align: bool = True):
    """Task-side checkpoint spans, parented on the coordinator context
    riding the barrier so the whole checkpoint forms one trace tree:
    emits the Align span (trigger → aligned at this subtask) and returns
    an open Snapshot builder the caller finishes at ack time."""
    parent = TraceContext.from_wire(barrier.trace)
    if align:
        (TRACER.span("checkpoint", "Align", parent=parent)
         .set_attribute("task", task_id)
         .set_attribute("checkpointId", barrier.checkpoint_id)
         .set_start_ts(int(barrier.timestamp * 1000))
         .finish())
    return (TRACER.span("checkpoint", "Snapshot", parent=parent)
            .set_attribute("task", task_id)
            .set_attribute("checkpointId", barrier.checkpoint_id))


class StreamTask:
    """Base: mailbox + lifecycle + checkpoint plumbing."""

    def __init__(self, task_id: str, ctx: OperatorContext,
                 writers: list[RecordWriter], reporter: TaskReporter,
                 config: Optional[Configuration] = None,
                 side_writers: Optional[dict[str, list[RecordWriter]]] = None):
        self.task_id = task_id
        self.ctx = ctx
        self.writers = writers
        self.side_writers = side_writers or {}
        self.reporter = reporter
        self.config = config or ctx.config
        self._mailbox: queue.Queue = queue.Queue()
        self._cancelled = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # owning-job identity (multi-tenant attribution): every event
        # this task emits — watchdog trips, fault events, flight dumps,
        # ledger samples — is tagged with this via the thread-local
        # dispatch context pinned at thread start (_run_safely)
        self.job_name = str(self.config.get(PipelineOptions.NAME) or "")
        self.operator_state = OperatorStateBackend()
        self._last_proc_time = 0
        self.io_timers = TaskIOTimers()
        # per-subtask progress epoch (stall supervision, runtime/
        # watchdog.py): the loop bumps it once per processed event; the
        # job-level TaskStallDetector flags a stale epoch with queued
        # input and routes the task into the restart path
        from .watchdog import TaskProgress
        self.progress = TaskProgress()
        metrics = getattr(ctx, "metrics", None)
        if metrics is not None and hasattr(metrics, "bind_io_timers"):
            metrics.bind_io_timers(self.io_timers)
        if metrics is not None and hasattr(metrics, "bind_progress"):
            metrics.bind_progress(self.progress)

    def all_writers(self):
        yield from self.writers
        for ws in self.side_writers.values():
            yield from ws

    def broadcast_all(self, element) -> None:
        for w in self.all_writers():
            w.broadcast(element)

    def make_tail_output(self) -> "_WriterFanout":
        return _WriterFanout(self.writers, self.ctx.metrics, self.side_writers)

    # -- mailbox (reference MailboxProcessor) ------------------------------
    def execute_in_mailbox(self, fn: Callable[[], None]) -> None:
        self._mailbox.put(fn)

    def _drain_mailbox(self) -> None:
        while True:
            try:
                self._mailbox.get_nowait()()
            except queue.Empty:
                return

    # -- control -----------------------------------------------------------
    def start(self) -> threading.Thread:
        # a cancelled task must unwind out of backpressured emits (failover
        # teardown toward a dead peer)
        for w in self.all_writers():
            w.cancel_event = self._cancelled
            w.io_timers = self.io_timers  # backpressured-time accounting
        self._thread = threading.Thread(target=self._run_safely,
                                        name=self.task_id, daemon=True)
        self._thread.start()
        return self._thread

    def cancel(self) -> None:
        self._cancelled.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread:
            self._thread.join(timeout)

    @property
    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run_safely(self) -> None:
        from .watchdog import PROGRESS
        from ..metrics.profiler import set_dispatch_context
        # pin the owning job for the whole task thread so watchdog/fault/
        # flight events are job-attributable even with the ledger off;
        # the operator chain narrows the operator part per dispatch
        set_dispatch_context(self.job_name, self.task_id)
        self.io_timers.start()
        self.progress.bump()  # deploy->start latency never reads as a stall
        PROGRESS.register(self.task_id, self.progress)
        try:
            self.invoke()
            self.reporter.task_finished(self.task_id)
        except BaseException as e:  # noqa: BLE001 - report everything
            if not self._cancelled.is_set():
                self.reporter.task_failed(self.task_id, e)
        finally:
            self.io_timers.stop()
            PROGRESS.unregister(self.task_id)

    def invoke(self) -> None:
        raise NotImplementedError

    def input_pending(self) -> bool:
        """Queued input this task COULD be processing right now — the
        stall detector's 'stalled, not idle' discriminator. Sources have
        no gate and are never flagged (a quiet source is idle by
        definition; its blocking sites are watchdogged individually)."""
        return False

    # -- helpers -----------------------------------------------------------
    def _advance_processing_time(self, chain: Optional[OperatorChain]) -> None:
        now = self.ctx.processing_time()
        if now > self._last_proc_time:
            self._last_proc_time = now
            if chain is not None:
                chain.advance_processing_time(now)


class SourceStreamTask(StreamTask):
    """Runs one source reader; checkpoints are injected here by the
    coordinator through the mailbox (reference triggerCheckpointAsync)."""

    def __init__(self, task_id: str, ctx: OperatorContext, source: Source,
                 reader: SourceReader, watermark_strategy: WatermarkStrategy,
                 chain: Optional[OperatorChain], writers: list[RecordWriter],
                 reporter: TaskReporter,
                 config: Optional[Configuration] = None):
        super().__init__(task_id, ctx, writers, reporter, config)
        self.source = source
        self.reader = reader
        self.ws = watermark_strategy
        self.chain = chain  # chained operators after the source, may be None
        self._restored_reader_state: Any = None
        # wall-clock spent per stage of the source loop (observability /
        # bench breakdown): read = generator/IO, emit = chain + backpressure
        self.stage_s: dict[str, float] = {"read": 0.0, "emit": 0.0}
        # watermark-alignment + admission-control observability
        self.alignment_pauses = 0
        self.alignment_max_overshoot_ms = 0
        # multi-tenant admission gate observability (cluster/isolation.py)
        self.sched_pauses = 0      # 1ms quota waits at the gate
        self.sched_sheds = 0       # micro-batches quarantined by overload
        self.current_batch_size = 0
        from collections import deque
        self.batch_size_history: deque = deque(maxlen=1024)
        # register in the alignment group at DEPLOY time with MIN, so no
        # group-mate can run ahead during the start-up window before this
        # source's first own report (all tasks are constructed before any
        # is started)
        align = getattr(reporter, "watermark_alignment", None)
        if (align is not None and watermark_strategy is not None
                and watermark_strategy.alignment_group):
            align.report(watermark_strategy.alignment_group, task_id,
                         MIN_TIMESTAMP,
                         watermark_strategy.alignment_max_drift_ms)

    def restore_state(self, snapshot: Optional[dict]) -> None:
        if not snapshot:
            return
        if snapshot.get("reader") is not None:
            self._restored_reader_state = snapshot["reader"]
        if self.chain is not None and snapshot.get("chain"):
            self.chain.initialize_state(snapshot["chain"])

    def _snapshot(self, barrier: CheckpointBarrier) -> None:
        sb = _barrier_spans(self.task_id, barrier, align=False)
        # ① emit barrier downstream first (source is the barrier origin)
        self.broadcast_all(barrier)
        # ② snapshot reader position + chained operators
        snap = {"reader": self.reader.snapshot(),
                "chain": (self.chain.snapshot_state(barrier.checkpoint_id)
                          if self.chain else None)}
        self.reporter.acknowledge_checkpoint(
            self.task_id, barrier.checkpoint_id, snap)
        sb.finish()

    def trigger_checkpoint(self, barrier: CheckpointBarrier) -> None:
        self.execute_in_mailbox(lambda: self._snapshot(barrier))

    def _admission_gate(self, out: Output) -> str:
        """Per-job micro-batch admission (cluster/isolation.py).

        Polls ``ISOLATION.try_admit`` before each read. ``"retry"``
        waits ~1ms per poll with the mailbox live and the wait counted
        as backpressure (the alignment-pause idiom); a shed verdict
        reads the batch anyway and quarantines it to the dead-letter
        side output under a typed ``OverloadShedError`` — counted and
        flight-recorded against THIS job only, never surfaced as a task
        failure (shedding is the bulkhead working, not the job dying).
        Returns ``"admitted"``, ``"shed"`` (caller continues its loop),
        or ``"stop"`` (cancelled / reader exhausted mid-shed)."""
        from ..cluster.isolation import ISOLATION, OverloadShedError
        from ..metrics.tracing import record_flight_event
        from .faults import FAULTS

        job = self.job_name
        waited = 0.0
        ISOLATION.note_waiting(job, +1)
        try:
            while True:
                # chaos sites: a sched.admit trip fails/hangs the gate
                # itself; a sched.shed trip forces a shed without overload
                FAULTS.fire("sched.admit")
                verdict = ("shed:injected" if FAULTS.check("sched.shed")
                           else ISOLATION.try_admit(job, waited))
                if verdict == "admit":
                    if waited > 0.0:
                        # throttle wait is attributed device-side so the
                        # ledger's per-job view shows quota pressure
                        from ..metrics.profiler import DEVICE_LEDGER
                        DEVICE_LEDGER.record(
                            "sched.throttle", waited * 1e3, job=job,
                            operator=self.task_id, kind="dispatch")
                        if TRACER.enabled:
                            end = now_ms()
                            (TRACER.span("sched", "Admit")
                             .set_attribute("job", job)
                             .set_attribute("task", self.task_id)
                             .set_attribute("waited_ms",
                                            round(waited * 1e3, 3))
                             .set_start_ts(end - int(waited * 1e3))
                             .finish(end))
                    return "admitted"
                if verdict == "retry":
                    if self._cancelled.is_set():
                        return "stop"
                    self.sched_pauses += 1
                    time.sleep(0.001)  # gated: mailbox stays live below
                    waited += 0.001
                    # quota-paused counts as backpressured, not idle: a
                    # competing tenant's consumption is what we wait on
                    self.io_timers.backpressured_s += 0.001
                    self._drain_mailbox()
                    self._advance_processing_time(self.chain)
                    continue
                # shed:* — quarantine the next batch to dead-letter
                reason = verdict.partition(":")[2] or "gate-timeout"
                batch = self.reader.read_batch(self.current_batch_size)
                if batch is None:
                    return "stop"
                if not batch.n:
                    time.sleep(0.001)  # nothing to shed; no tight spin
                    self.io_timers.idle_s += 0.001
                    return "shed"
                err = OverloadShedError(job, reason, waited)
                ISOLATION.note_shed(job, batch.n, reason)
                from ..metrics.device import DEVICE_STATS
                DEVICE_STATS.note_dead_letter(batch.n)
                # side-emitted when a dead-letter edge is wired on this
                # vertex; otherwise the counters + flight event are the
                # record (device_window._dead_letter semantics)
                try:
                    out.emit_side("dead-letter", batch)
                except NotImplementedError:
                    pass
                record_flight_event(
                    "overload-shed", job=job, task=self.task_id,
                    reason=reason, records=batch.n, error=repr(err))
                if TRACER.enabled:
                    (TRACER.span("sched", "Shed")
                     .set_attribute("job", job)
                     .set_attribute("task", self.task_id)
                     .set_attribute("reason", reason)
                     .set_attribute("records", batch.n)
                     .finish())
                self.sched_sheds += 1
                self.progress.bump()  # shedding IS progress, not a stall
                return "shed"
        finally:
            ISOLATION.note_waiting(job, -1)

    def invoke(self) -> None:
        from ..cluster.isolation import ISOLATION
        batch_size = self.config.get(PipelineOptions.BATCH_SIZE)
        wm_interval = self.config.get(PipelineOptions.AUTO_WATERMARK_INTERVAL)
        latency_interval = self.config.get(MetricOptions.LATENCY_INTERVAL)
        last_marker_emit = 0.0
        idle_timeout = self.ws.idle_timeout
        if self._restored_reader_state is not None:
            self.reader.restore(self._restored_reader_state)
        gen = self.ws.create_generator()
        out: Output = self.make_tail_output()
        if self.chain is not None:
            self.chain.open()
        last_wm_emit = 0.0
        last_wm = MIN_TIMESTAMP
        last_data_time = time.time()
        idle = False

        # watermark alignment (reference SourceCoordinator announceCombined-
        # Watermark): sources in the strategy's group pause when ahead of
        # group-min + drift; idle sources report MAX and don't hold it back
        align = getattr(self.reporter, "watermark_alignment", None)
        align_group = self.ws.alignment_group if align is not None else None
        align_drift = self.ws.alignment_max_drift_ms
        from .alignment import MAX_WATERMARK as _ALIGN_MAX

        # admission control (reference BufferDebloater): batch size tracks
        # throughput x target-latency so in-flight bytes stay bounded
        adaptive = self.config.get(PipelineOptions.ADAPTIVE_BATCH)
        if adaptive:
            target_s = self.config.get(PipelineOptions.ADAPTIVE_TARGET_LATENCY)
            min_batch = self.config.get(PipelineOptions.ADAPTIVE_MIN_BATCH)
            max_batch = self.config.get(PipelineOptions.ADAPTIVE_MAX_BATCH)
        self.current_batch_size = batch_size

        while not self._cancelled.is_set():
            self._drain_mailbox()
            if align_group is not None:
                cur = gen.current_watermark()
                allowed = align.report(align_group, self.task_id,
                                       _ALIGN_MAX if idle else cur,
                                       align_drift)
                if not idle and cur > allowed:
                    self.alignment_pauses += 1
                    if allowed - align_drift > MIN_TIMESTAMP:
                        # overshoot is only meaningful once the group min
                        # reflects a real report, not deploy-time MIN
                        self.alignment_max_overshoot_ms = max(
                            self.alignment_max_overshoot_ms, cur - allowed)
                    time.sleep(0.001)  # paused: mailbox stays live above
                    # paused-by-group counts as backpressured, not idle:
                    # downstream consumption is what the pause waits on
                    self.io_timers.backpressured_s += 0.001
                    # pausing stops READING only — processing-time timers
                    # in the chained operators must keep firing
                    self._advance_processing_time(self.chain)
                    continue
            # multi-tenant admission gate (cluster/isolation.py): under
            # contention this job spends one quota credit per micro-batch;
            # sustained overload or an open breaker sheds instead
            if ISOLATION.enabled:
                verdict = self._admission_gate(out)
                if verdict == "stop":
                    break
                if verdict == "shed":
                    continue
            t0 = time.perf_counter()
            batch = self.reader.read_batch(self.current_batch_size)
            read_dt = time.perf_counter() - t0
            self.stage_s["read"] += read_dt
            self.io_timers.busy_s += read_dt
            if batch is None:  # exhausted (bounded)
                break
            if batch.n:
                if self.ctx.metrics is not None:
                    self.ctx.metrics.records_in.inc(batch.n)
                batch = self.ws.assign_timestamps(batch)
                gen.on_batch(batch)
                last_data_time = time.time()
                if idle:
                    idle = False
                    self.broadcast_all(WatermarkStatus(True))
                t0 = time.perf_counter()
                if self.chain is not None:
                    self.chain.process_batch(batch)
                else:
                    out.emit(batch)
                emit_dt = time.perf_counter() - t0
                self.stage_s["emit"] += emit_dt
                self.io_timers.busy_s += emit_dt
                self.progress.bump()
                if TRACER.enabled:
                    # one mailbox-loop cycle: read + chain/emit phases
                    end = now_ms()
                    (TRACER.span("task", "SourceBatch")
                     .set_attribute("task", self.task_id)
                     .set_attribute("records", batch.n)
                     .set_attribute("read_ms", round(read_dt * 1e3, 3))
                     .set_attribute("emit_ms", round(emit_dt * 1e3, 3))
                     .set_start_ts(end - int((read_dt + emit_dt) * 1e3))
                     .finish(end))
                if adaptive:
                    # desired = throughput x target; EMA toward it. At the
                    # fixpoint one batch takes exactly target seconds.
                    tput = batch.n / max(read_dt + emit_dt, 1e-9)
                    desired = tput * target_s
                    self.current_batch_size = int(min(max(
                        0.5 * self.current_batch_size + 0.5 * desired,
                        min_batch), max_batch))
                    self.batch_size_history.append(self.current_batch_size)
            else:
                time.sleep(0.001)  # unbounded source, nothing available
                self.io_timers.idle_s += 0.001
                if (idle_timeout is not None and not idle
                        and time.time() - last_data_time > idle_timeout):
                    idle = True
                    self.broadcast_all(WatermarkStatus(False))
            now = time.time()
            if now - last_wm_emit >= wm_interval:
                last_wm_emit = now
                wm = gen.current_watermark()
                if wm > last_wm and not idle:
                    last_wm = wm
                    if self.chain is not None:
                        self.chain.process_watermark(Watermark(wm))
                    else:
                        out.emit_watermark(Watermark(wm))
            if (latency_interval > 0
                    and now - last_marker_emit >= latency_interval):
                # end-to-end latency probe (reference latencyTrackingInterval
                # in StreamSource): rides the chain so every operator
                # records source->here latency before forwarding
                last_marker_emit = now
                marker = LatencyMarker(now, self.task_id,
                                       self.ctx.subtask_index)
                if self.chain is not None:
                    self.chain.process_latency_marker(marker)
                else:
                    out.emit_latency_marker(marker)
            self._advance_processing_time(self.chain)

        if align_group is not None:
            # finished/cancelled source must not hold its group back
            align.unregister(align_group, self.task_id)
        if not self._cancelled.is_set():
            self._drain_mailbox()
            # bounded source done: flush event time, finish chain, close edges
            final_wm = MAX_WATERMARK
            if self.chain is not None:
                self.chain.process_watermark(final_wm)
                self.chain.finish()
                self.chain.close()
            else:
                out.emit_watermark(final_wm)
            self.broadcast_all(EndOfInput())
        self.reader.close()


class TwoInputStreamTask(StreamTask):
    """Two gates -> two-input head operator chain -> writers (reference
    TwoInputStreamTask + StreamTwoInputProcessor). Each gate aligns barriers
    over its own channels; the task snapshot fires only once BOTH gates have
    delivered the barrier for the same checkpoint (the two-gate alignment of
    SingleCheckpointBarrierHandler), holding back the already-aligned gate."""

    def __init__(self, task_id: str, ctx: OperatorContext, gate1: InputGate,
                 gate2: InputGate, chain: OperatorChain,
                 writers: list[RecordWriter], reporter: TaskReporter,
                 config: Optional[Configuration] = None):
        super().__init__(task_id, ctx, writers, reporter, config)
        self.gates = [gate1, gate2]
        self.chain = chain
        self._gate_barrier: list = [None, None]
        self._unaligned_pending = None
        self._restored_inflight: list[list] = [[], []]

    def restore_state(self, snapshot: Optional[dict]) -> None:
        if not snapshot:
            return
        if snapshot.get("chain"):
            self.chain.initialize_state(snapshot["chain"])
        self._restored_inflight = [list(snapshot.get("inflight1", ())),
                                   list(snapshot.get("inflight2", ()))]

    def _complete_barrier(self, barrier: CheckpointBarrier) -> None:
        sb = _barrier_spans(self.task_id, barrier)
        self._gate_barrier = [None, None]
        self.broadcast_all(barrier)
        snap = {"chain": self.chain.snapshot_state(barrier.checkpoint_id)}
        self.reporter.acknowledge_checkpoint(
            self.task_id, barrier.checkpoint_id, snap)
        sb.finish()

    def _on_barrier(self, gi: int, barrier: CheckpointBarrier) -> None:
        if self.gates[gi].capture_active:
            # unaligned: barrier overtook on gate gi — snapshot now, start
            # capturing the sibling gate too, ack when both drained
            if self._unaligned_pending is not None:
                old_b, _ = self._unaligned_pending
                self._unaligned_pending = None
                self.reporter.declined_checkpoint(
                    self.task_id, old_b.checkpoint_id,
                    "overtaken by a newer unaligned checkpoint")
            self.broadcast_all(barrier)
            snap = {"chain": self.chain.snapshot_state(barrier.checkpoint_id)}
            self.gates[1 - gi].begin_capture(barrier)
            self._unaligned_pending = (barrier, snap)
            self._maybe_finish_unaligned()
            return
        self._gate_barrier[gi] = barrier
        self._maybe_complete_barrier()

    def _maybe_finish_unaligned(self) -> None:
        if self._unaligned_pending is None:
            return
        if not all(g.capture_complete for g in self.gates):
            return
        barrier, snap = self._unaligned_pending
        self._unaligned_pending = None
        snap["inflight1"] = self.gates[0].take_captured()
        snap["inflight2"] = self.gates[1].take_captured()
        self.reporter.acknowledge_checkpoint(
            self.task_id, barrier.checkpoint_id, snap)

    def _maybe_complete_barrier(self) -> None:
        b0, b1 = self._gate_barrier
        # an exhausted input never delivers barriers: don't wait on it
        if b0 is not None and b1 is None and self.gates[1].all_ended():
            b1 = b0
        if b1 is not None and b0 is None and self.gates[0].all_ended():
            b0 = b1
        if b0 is None or b1 is None:
            return  # hold the aligned gate (skipped in the poll loop)
        if b0.checkpoint_id != b1.checkpoint_id:
            # a newer checkpoint overtook on one side: adopt the newer one
            newer = max(b0, b1, key=lambda b: b.checkpoint_id)
            held = self._gate_barrier
            self._gate_barrier = [None, None]
            for g in (0, 1):
                if held[g] is newer:
                    self._gate_barrier[g] = newer
            return
        self._complete_barrier(b0)

    def invoke(self) -> None:
        self.chain.open()
        for gi in (0, 1):
            for b in self._restored_inflight[gi]:
                self.chain.process_batch_n(gi, b)
        self._restored_inflight = [[], []]
        rr = 0
        while not self._cancelled.is_set():
            self._drain_mailbox()
            self._maybe_finish_unaligned()
            if any(b is not None for b in self._gate_barrier):
                # the other input may have ended while a barrier was held
                self._maybe_complete_barrier()
            ev = gi = None
            for off in range(2):
                g = (rr + off) % 2
                if self._gate_barrier[g] is not None:
                    continue  # aligned, waiting for the other gate
                ev = self.gates[g].poll()
                if ev is not None:
                    gi = g
                    rr = 1 - g
                    break
            if ev is None:
                if all(g.all_ended() for g in self.gates):
                    break
                self._advance_processing_time(self.chain)
                time.sleep(0.0005)
                self.io_timers.idle_s += 0.0005
                continue
            t0 = time.perf_counter()
            if ev.kind == "batch":
                if self.ctx.metrics is not None:
                    self.ctx.metrics.records_in.inc(ev.value.n)
                self.chain.process_batch_n(gi, ev.value)
            elif ev.kind == "watermark":
                self.chain.process_watermark_n(gi, ev.value)
            elif ev.kind == "barrier":
                self._on_barrier(gi, ev.value)
            elif ev.kind == "latency":
                self.chain.process_latency_marker(ev.value)
            elif ev.kind == "idle":
                self.broadcast_all(ev.value)
            self.io_timers.busy_s += time.perf_counter() - t0
            self.progress.bump()
            self._advance_processing_time(self.chain)

        if not self._cancelled.is_set():
            self._maybe_finish_unaligned()
            self.chain.finish()
            self.chain.close()
            self.broadcast_all(EndOfInput())

    def input_pending(self) -> bool:
        return any(ch.size() > 0 for g in self.gates for ch in g.channels)


class OneInputStreamTask(StreamTask):
    """Gate -> operator chain -> writers (reference OneInputStreamTask)."""

    def __init__(self, task_id: str, ctx: OperatorContext, gate: InputGate,
                 chain: OperatorChain, writers: list[RecordWriter],
                 reporter: TaskReporter,
                 config: Optional[Configuration] = None):
        super().__init__(task_id, ctx, writers, reporter, config)
        self.gate = gate
        self.chain = chain
        self._restored_inflight: list = []
        self._unaligned_pending = None  # (barrier, snapshot) awaiting capture

    def restore_state(self, snapshot: Optional[dict]) -> None:
        if not snapshot:
            return
        if snapshot.get("chain"):
            self.chain.initialize_state(snapshot["chain"])
        # unaligned checkpoint: in-flight pre-barrier batches replay first
        self._restored_inflight = list(snapshot.get("inflight", ()))

    def _on_barrier(self, barrier: CheckpointBarrier) -> None:
        """Broadcast downstream first, then snapshot (reference
        SubtaskCheckpointCoordinatorImpl.checkpointState). Aligned: ack
        immediately. Unaligned (barrier overtook): the state snapshot is
        taken NOW but the ack waits until the other channels' pre-barrier
        in-flight data has been captured (reference ChannelStateWriter
        completing the channel state future)."""
        if self._unaligned_pending is not None:
            # a newer checkpoint overtook before capture finished: the older
            # one can no longer complete on this task
            old_b, _ = self._unaligned_pending
            self._unaligned_pending = None
            self.reporter.declined_checkpoint(
                self.task_id, old_b.checkpoint_id,
                "overtaken by a newer unaligned checkpoint")
        sb = _barrier_spans(self.task_id, barrier)
        self.broadcast_all(barrier)
        snap = {"chain": self.chain.snapshot_state(barrier.checkpoint_id)}
        if self.gate.capture_active and not self.gate.capture_complete:
            self._unaligned_pending = (barrier, snap)
            sb.set_attribute("unaligned", True).finish()
            return
        if self.gate.capture_active:  # capture already complete (1 channel)
            snap["inflight"] = self.gate.take_captured()
        self.reporter.acknowledge_checkpoint(
            self.task_id, barrier.checkpoint_id, snap)
        sb.finish()

    def _maybe_finish_unaligned(self) -> None:
        if self._unaligned_pending is None:
            return
        if not self.gate.capture_complete:
            return
        barrier, snap = self._unaligned_pending
        self._unaligned_pending = None
        snap["inflight"] = self.gate.take_captured()
        self.reporter.acknowledge_checkpoint(
            self.task_id, barrier.checkpoint_id, snap)

    def invoke(self) -> None:
        self.chain.open()
        for batch in self._restored_inflight:
            # replayed in-flight data precedes any new input
            self.chain.process_batch(batch)
        self._restored_inflight = []
        while not self._cancelled.is_set():
            self._drain_mailbox()
            ev = self.gate.poll()
            if ev is None:
                self._maybe_finish_unaligned()
                if self.gate.all_ended():
                    break
                self._advance_processing_time(self.chain)
                time.sleep(0.0005)
                self.io_timers.idle_s += 0.0005
                continue
            t0 = time.perf_counter()
            if ev.kind == "batch":
                if self.ctx.metrics is not None:
                    self.ctx.metrics.records_in.inc(ev.value.n)
                self.chain.process_batch(ev.value)
            elif ev.kind == "watermark":
                self.chain.process_watermark(ev.value)
            elif ev.kind == "barrier":
                self._on_barrier(ev.value)
            elif ev.kind == "latency":
                # through the chain, not past it: every operator records
                # its source->here latency before forwarding downstream
                self.chain.process_latency_marker(ev.value)
            elif ev.kind == "idle":
                self.broadcast_all(ev.value)
            self.io_timers.busy_s += time.perf_counter() - t0
            self.progress.bump()
            self._maybe_finish_unaligned()
            self._advance_processing_time(self.chain)

        if not self._cancelled.is_set():
            self._maybe_finish_unaligned()
            self.chain.finish()
            self.chain.close()
            self.broadcast_all(EndOfInput())

    def input_pending(self) -> bool:
        return any(ch.size() > 0 for ch in self.gate.channels)
