"""Record writer + stream partitioners.

Analog of the reference's RecordWriter (flink-runtime io/network/api/writer/
RecordWriter.java:51) and the partitioner family
(flink-streaming-java runtime/partitioner/: KeyGroupStreamPartitioner,
RebalancePartitioner, RescalePartitioner, BroadcastPartitioner,
ForwardPartitioner, ShufflePartitioner, GlobalPartitioner,
CustomPartitionerWrapper). Partitioning is batch-granular where the reference
is record-granular: a keyed exchange splits one batch into per-subtask
sub-batches in one vectorized pass; rebalance rotates whole batches.

Watermarks, barriers, and end-of-input always broadcast to every output
channel (as in the reference), which is what makes downstream alignment and
min-combine correct.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..core.elements import CheckpointBarrier, EndOfInput, LatencyMarker, \
    Watermark, WatermarkStatus
from ..core.keygroups import hash_batch, key_groups_for_hash_batch, \
    operator_index_for_key_group
from ..core.records import RecordBatch
from .channels import Channel
from .faults import FAULTS, fire_with_retries

__all__ = [
    "StreamPartitioner", "ForwardPartitioner", "RebalancePartitioner",
    "RescalePartitioner", "BroadcastPartitioner", "ShufflePartitioner",
    "GlobalPartitioner", "KeyGroupPartitioner", "CustomPartitioner",
    "RecordWriter", "WriterCancelled",
]


class StreamPartitioner:
    """Decides which downstream subtask(s) receive a batch."""

    name = "partitioner"
    is_broadcast = False
    is_pointwise = False  # pointwise (forward/rescale) vs all-to-all

    def route(self, batch: RecordBatch, num_channels: int,
              subtask_index: int) -> Sequence[tuple[int, RecordBatch]]:
        raise NotImplementedError


class ForwardPartitioner(StreamPartitioner):
    name = "forward"
    is_pointwise = True

    def route(self, batch, num_channels, subtask_index):
        return [(subtask_index % num_channels, batch)]


class RebalancePartitioner(StreamPartitioner):
    """Round-robin whole batches (record-level RR would shred batches)."""

    name = "rebalance"

    def __init__(self):
        self._next = -1

    def route(self, batch, num_channels, subtask_index):
        self._next = (self._next + 1) % num_channels
        return [(self._next, batch)]


class RescalePartitioner(RebalancePartitioner):
    """Local round-robin within the pointwise group (reference semantics;
    locality is enforced by the edge wiring, round-robin is the same)."""

    name = "rescale"
    is_pointwise = True


class BroadcastPartitioner(StreamPartitioner):
    name = "broadcast"
    is_broadcast = True

    def route(self, batch, num_channels, subtask_index):
        return [(i, batch) for i in range(num_channels)]


class ShufflePartitioner(StreamPartitioner):
    name = "shuffle"

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def route(self, batch, num_channels, subtask_index):
        return [(self._rng.randrange(num_channels), batch)]


class GlobalPartitioner(StreamPartitioner):
    name = "global"

    def route(self, batch, num_channels, subtask_index):
        return [(0, batch)]


class KeyGroupPartitioner(StreamPartitioner):
    """Hash -> key group -> downstream subtask, vectorized over the batch
    (reference KeyGroupStreamPartitioner.selectChannel)."""

    name = "hash"

    def __init__(self, key_extractor: Callable[[RecordBatch], np.ndarray],
                 max_parallelism: int):
        self._key_extractor = key_extractor
        self.max_parallelism = max_parallelism

    def route(self, batch, num_channels, subtask_index):
        if num_channels == 1:
            # every key group maps to subtask 0: forward the handle without
            # touching the columns (device batches stay on device)
            return [(0, batch)]
        keys = self._key_extractor(batch)
        hashes = hash_batch(keys)
        groups = key_groups_for_hash_batch(hashes, self.max_parallelism)
        # subtask = kg * parallelism // max_parallelism, vectorized
        targets = (groups.astype(np.int64) * num_channels
                   // self.max_parallelism).astype(np.int32)
        parts = batch.split_by(targets, num_channels)
        return [(i, p) for i, p in enumerate(parts) if p.n]


class CustomPartitioner(StreamPartitioner):
    name = "custom"

    def __init__(self, fn: Callable[[Any, int], int],
                 key_extractor: Callable[[RecordBatch], np.ndarray]):
        self._fn = fn
        self._key_extractor = key_extractor

    def route(self, batch, num_channels, subtask_index):
        keys = self._key_extractor(batch)
        targets = np.fromiter(
            (self._fn(k, num_channels) for k in keys),
            dtype=np.int32, count=batch.n)
        parts = batch.split_by(targets, num_channels)
        return [(i, p) for i, p in enumerate(parts) if p.n]


class WriterCancelled(Exception):
    """Raised out of a blocked emit when the owning task is cancelled —
    how a task stuck on backpressure toward a dead peer unwinds during
    failover (reference: the availability future completing exceptionally
    on cancellation)."""


class RecordWriter:
    """Writes one operator output to its downstream channels.

    ``stall_timeout`` caps the TOTAL time one element may spend blocked
    on a full downstream channel (``task.backpressure.stall-timeout``):
    a stuck-but-alive peer — one that holds the connection open but never
    drains — then raises :class:`StallError` into the supervisor instead
    of wedging this task forever. The element is never dropped: the task
    fails, and restart-from-checkpoint replays it."""

    def __init__(self, channels: list[Channel], partitioner: StreamPartitioner,
                 subtask_index: int, put_timeout: float = 0.1,
                 stall_timeout: float = 0.0):
        self.channels = channels
        self.partitioner = partitioner
        self.subtask_index = subtask_index
        self._put_timeout = put_timeout
        self.stall_timeout = stall_timeout  # 0 = unbounded wait
        self.cancel_event = None  # set by the task that owns this writer
        self.io_timers = None     # set by the task: backpressure accounting

    def _put_blocking(self, channel: Channel, element: Any) -> None:
        # Bounded queue full = backpressure; spin with timeout so the task
        # thread stays interruptible (reference: availability future).
        # Fast path first: the uncontended put must not pay the clock.
        if channel.put(element, timeout=0):
            return
        t0 = time.perf_counter()
        try:
            while not channel.put(element, timeout=self._put_timeout):
                if (self.cancel_event is not None
                        and self.cancel_event.is_set()):
                    raise WriterCancelled()
                if (self.stall_timeout
                        and time.perf_counter() - t0 > self.stall_timeout):
                    from ..metrics.device import DEVICE_STATS
                    from .watchdog import StallError
                    DEVICE_STATS.note_stall("channel.backpressure")
                    raise StallError("channel.backpressure",
                                     self.stall_timeout,
                                     scope=f"subtask {self.subtask_index}")
        finally:
            if self.io_timers is not None:
                self.io_timers.backpressured_s += time.perf_counter() - t0

    def emit(self, batch: RecordBatch) -> None:
        if not batch.n:
            return
        # fault site channel.send (docs/ROBUSTNESS.md): a transient trip
        # models one failed flush — retried in place, counted as a retry;
        # a persistent trip fails the task and recovers through the job
        # restart strategy exactly like a severed transport connection
        if FAULTS.enabled:
            fire_with_retries("channel.send")
        for idx, part in self.partitioner.route(
                batch, len(self.channels), self.subtask_index):
            self._put_blocking(self.channels[idx], part)

    def broadcast(self, element) -> None:
        """Watermarks/barriers/status go to every channel."""
        for ch in self.channels:
            self._put_blocking(ch, element)

    def emit_watermark(self, wm: Watermark) -> None:
        self.broadcast(wm)

    def emit_barrier(self, barrier: CheckpointBarrier) -> None:
        self.broadcast(barrier)

    def emit_end(self) -> None:
        self.broadcast(EndOfInput())


class FeedbackRecordWriter(RecordWriter):
    """Writer for an iteration back edge: only RECORDS and EndOfInput flow
    into the loop (reference StreamIterationTail). Watermarks and barriers
    are dropped — event time does not advance through feedback (the head's
    gate keeps feedback channels idle), and a barrier circulating the loop
    would re-trigger the head's alignment (iterations are therefore not
    checkpointable; deploy rejects the combination loudly)."""

    def broadcast(self, element) -> None:
        if isinstance(element, EndOfInput):
            super().broadcast(element)
        # Watermark / WatermarkStatus / CheckpointBarrier / LatencyMarker:
        # intentionally dropped on the back edge
