"""Internal timer service: per-key event/processing-time timers.

Analog of the reference's InternalTimerServiceImpl
(flink-streaming-java api/operators/InternalTimerServiceImpl.java:44,
InternalTimeServiceManagerImpl.java:58): timers are (timestamp, key, namespace)
triples, deduplicated, partitioned by key group so they snapshot/restore with
keyed state and re-shard on rescale. Event-time timers fire when the operator's
watermark advances past them; processing-time timers when wall-clock advances
(driven by the task's step loop rather than a JVM timer thread).

The generic host implementation is a binary heap + dedup set. The device
window path doesn't use per-key timers at all — pane boundaries make firing a
vectorized comparison (SURVEY.md §7 hard-parts: 'per-key timers at 10M keys').
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

from ..core.keygroups import KeyGroupRange, assign_to_key_group
from ..core.records import MIN_TIMESTAMP

__all__ = ["Timer", "InternalTimerService", "TimerSerializationMixin"]


@dataclass(frozen=True, order=True)
class Timer:
    timestamp: int
    key: Any
    namespace: Any = None


class InternalTimerService:
    """One named timer service per operator (reference: one per namespace
    serializer); confined to the task thread."""

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 on_event_time: Callable[[Timer], None],
                 on_processing_time: Callable[[Timer], None]):
        self.key_group_range = key_group_range
        self.max_parallelism = max_parallelism
        self._on_event_time = on_event_time
        self._on_processing_time = on_processing_time
        # heap entries are (ts, seq, kg, key, ns); seq breaks ties so keys
        # and namespaces (possibly mutually non-comparable) are never compared
        self._event_heap: list[tuple] = []
        self._event_set: set[tuple[int, Any, Any]] = set()
        self._proc_heap: list[tuple] = []
        self._proc_set: set[tuple[int, Any, Any]] = set()
        self._seq = 0
        self.current_watermark = MIN_TIMESTAMP

    # -- registration (row path; keyed by caller-provided key) -------------
    def register_event_time_timer(self, key: Any, timestamp: int,
                                  namespace: Any = None) -> None:
        t = (int(timestamp), key, namespace)
        if t not in self._event_set:
            self._event_set.add(t)
            kg = assign_to_key_group(key, self.max_parallelism)
            self._seq += 1
            heapq.heappush(self._event_heap,
                           (int(timestamp), self._seq, kg, key, namespace))

    def register_processing_time_timer(self, key: Any, timestamp: int,
                                       namespace: Any = None) -> None:
        t = (int(timestamp), key, namespace)
        if t not in self._proc_set:
            self._proc_set.add(t)
            kg = assign_to_key_group(key, self.max_parallelism)
            self._seq += 1
            heapq.heappush(self._proc_heap,
                           (int(timestamp), self._seq, kg, key, namespace))

    def delete_event_time_timer(self, key: Any, timestamp: int,
                                namespace: Any = None) -> None:
        self._event_set.discard((int(timestamp), key, namespace))

    def delete_processing_time_timer(self, key: Any, timestamp: int,
                                     namespace: Any = None) -> None:
        self._proc_set.discard((int(timestamp), key, namespace))

    # -- firing ------------------------------------------------------------
    def advance_watermark(self, watermark: int) -> None:
        """Fire all event-time timers <= watermark (reference
        InternalTimerServiceImpl.advanceWatermark)."""
        self.current_watermark = watermark
        while self._event_heap and self._event_heap[0][0] <= watermark:
            ts, _seq, _kg, key, ns = heapq.heappop(self._event_heap)
            ident = (ts, key, ns)
            if ident in self._event_set:  # not deleted
                self._event_set.discard(ident)
                self._on_event_time(Timer(ts, key, ns))

    def advance_processing_time(self, now_ms: int) -> None:
        while self._proc_heap and self._proc_heap[0][0] <= now_ms:
            ts, _seq, _kg, key, ns = heapq.heappop(self._proc_heap)
            ident = (ts, key, ns)
            if ident in self._proc_set:
                self._proc_set.discard(ident)
                self._on_processing_time(Timer(ts, key, ns))

    def next_processing_time(self) -> Optional[int]:
        while self._proc_heap:
            ts, _seq, _kg, key, ns = self._proc_heap[0]
            if (ts, key, ns) in self._proc_set:
                return ts
            heapq.heappop(self._proc_heap)
        return None

    # -- checkpointing: timers snapshot per key group ----------------------
    def snapshot(self) -> dict:
        def dump(heap, live):
            per_kg: dict[int, list] = {}
            for ts, _seq, kg, key, ns in heap:
                if (ts, key, ns) in live:
                    per_kg.setdefault(kg, []).append((ts, key, ns))
            return per_kg

        return {"event": dump(self._event_heap, self._event_set),
                "proc": dump(self._proc_heap, self._proc_set),
                "watermark": self.current_watermark}

    def restore(self, snapshots: Iterable[dict]) -> None:
        self._event_heap, self._event_set = [], set()
        self._proc_heap, self._proc_set = [], set()
        for snap in snapshots:
            for kind in ("event", "proc"):
                heap = self._event_heap if kind == "event" else self._proc_heap
                live = self._event_set if kind == "event" else self._proc_set
                for kg, timers in snap.get(kind, {}).items():
                    kg = int(kg)
                    if kg not in self.key_group_range:
                        continue
                    for ts, key, ns in timers:
                        ident = (int(ts), key, ns)
                        if ident not in live:
                            live.add(ident)
                            self._seq += 1
                            heapq.heappush(heap,
                                           (int(ts), self._seq, kg, key, ns))
            self.current_watermark = max(self.current_watermark,
                                         snap.get("watermark", MIN_TIMESTAMP))
