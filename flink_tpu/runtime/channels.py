"""In-process channels, input gates, barrier alignment, watermark valve.

Local-exchange analog of the reference's network stack + input processing:
bounded queues stand in for credit-based Netty channels (a full queue IS
backpressure, like credit exhaustion in RemoteInputChannel.java:68);
``InputGate`` merges channels like SingleInputGate; barrier alignment follows
SingleCheckpointBarrierHandler.java:64 (block a channel once its barrier
arrives until all channels' barriers arrive — blocking here is simply not
polling, the queue itself buffers); watermark min-combine with idleness
follows StatusWatermarkValve.java:40. Inter-host transport plugs in behind
the same Channel interface (cluster/transport.py).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Iterable, Optional

from ..core.elements import (
    CheckpointBarrier, EndOfInput, LatencyMarker, Watermark, WatermarkStatus,
)
from ..core.records import MIN_TIMESTAMP, RecordBatch
from .faults import FAULTS

__all__ = ["Channel", "LocalChannel", "InputGate", "IterationGate",
           "GateEvent"]

DEFAULT_CAPACITY = 64  # queued elements per channel before backpressure


class Channel:
    """One logical edge subtask->subtask."""

    def put(self, element: Any, timeout: Optional[float] = None) -> bool:
        raise NotImplementedError

    def poll(self) -> Optional[Any]:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError


class LocalChannel(Channel):
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._q: queue.Queue = queue.Queue(maxsize=capacity)

    def put(self, element: Any, timeout: Optional[float] = None) -> bool:
        if FAULTS.enabled and FAULTS.check("channel.backpressure"):
            # drop-style site: report "queue full" once — the writer's
            # bounded-queue spin treats it exactly like real credit
            # exhaustion and retries, so chaos runs exercise the
            # backpressure path deterministically without losing data
            return False
        try:
            self._q.put(element, timeout=timeout)
            return True
        except queue.Full:
            return False

    def poll(self) -> Optional[Any]:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def size(self) -> int:
        return self._q.qsize()

    def drain(self) -> list:
        out = []
        while True:
            e = self.poll()
            if e is None:
                return out
            out.append(e)


class ReplayableChannel(Channel):
    """Blocking-partition channel for bounded (batch) execution: writes
    append to a persistent list (the SortMergeResultPartition analog —
    in-memory here), reads advance a per-reader cursor WITHOUT consuming,
    so a speculative attempt of the consumer can re-read from the start
    via ``clone_reader``. Unbounded by design: a blocking exchange
    materializes the producer's whole output before the consumer starts.
    """

    def __init__(self, items: Optional[list] = None,
                 lock: Optional[threading.Lock] = None):
        self._items: list = items if items is not None else []
        self._lock = lock or threading.Lock()
        self._cursor = 0
        self._sealed = False

    def put(self, element: Any, timeout: Optional[float] = None) -> bool:
        with self._lock:
            if self._sealed:
                # a speculation loser may wake from a blocking call after
                # its race was settled; its late writes must not corrupt
                # the adopted partition
                return True
            self._items.append(element)
        return True

    def poll(self) -> Optional[Any]:
        with self._lock:
            if self._cursor >= len(self._items):
                return None
            e = self._items[self._cursor]
            self._cursor += 1
            return e

    def size(self) -> int:
        with self._lock:
            return len(self._items) - self._cursor

    def drain(self) -> list:
        with self._lock:
            out = self._items[self._cursor:]
            self._cursor = len(self._items)
            return out

    # -- batch-mode extensions ------------------------------------------
    def clone_reader(self) -> "ReplayableChannel":
        """A fresh cursor over the SAME partition (speculative re-read)."""
        return ReplayableChannel(self._items, self._lock)

    def adopt_items(self, other: "ReplayableChannel") -> None:
        """Replace this partition's contents with another attempt's output
        and SEAL it against the losing attempt's late writes (the
        speculation winner's partition becomes THE partition)."""
        with self._lock:
            self._sealed = True
            self._items[:] = list(other._items)
            self._cursor = 0


@dataclass
class GateEvent:
    """What the gate hands the task: either data/watermark to process, a fully
    aligned barrier (snapshot now), or end-of-input."""

    kind: str  # "batch" | "watermark" | "barrier" | "end" | "latency" | "idle"
    value: Any = None
    channel: int = -1


class InputGate:
    """Merges N input channels with barrier alignment + watermark valve.

    Barrier modes (reference SingleCheckpointBarrierHandler.java:64 /
    CheckpointBarrierTracker / alternating aligned-unaligned):
    * aligned exactly-once (default): a channel that delivered its barrier
      is blocked until every channel's barrier arrived;
    * at-least-once (aligned=False): barriers counted, nothing blocks;
    * unaligned (unaligned=True): the FIRST barrier fires immediately and
      pre-barrier batches still queued on the other channels are captured
      into the checkpoint as in-flight data while processing continues;
    * alignment timeout (alignment_timeout_s > 0): an aligned checkpoint
      escalates to unaligned when alignment stalls longer than the timeout
      (reference BarrierAlignmentUtil timeout escalation).
    """

    def __init__(self, channels: list[Channel], aligned: bool = True,
                 unaligned: bool = False, alignment_timeout_s: float = 0.0):
        self.channels = channels
        self.aligned = aligned
        self.unaligned = unaligned
        self.alignment_timeout_s = alignment_timeout_s
        n = len(channels)
        self._blocked = [False] * n          # barrier-aligned channels
        self._ended = [False] * n
        self._wm = [MIN_TIMESTAMP] * n       # per-channel watermark
        self._active = [True] * n            # idleness per channel
        self._pending_barrier: Optional[CheckpointBarrier] = None
        self._barrier_seen: set[int] = set()
        self._combined_wm = MIN_TIMESTAMP
        self._rr = 0                         # fair round-robin pointer
        self.alignment_start: float = 0.0
        # unaligned capture state
        self._capturing: set[int] = set()    # channels still pre-barrier
        self._capture_barrier: Optional[CheckpointBarrier] = None
        self.captured: list = []             # in-flight elements

    # -- unaligned capture -------------------------------------------------
    @property
    def capture_active(self) -> bool:
        return self._capture_barrier is not None

    @property
    def capture_complete(self) -> bool:
        return self._capture_barrier is not None and not self._capturing

    def take_captured(self) -> list:
        out = self.captured
        self.captured = []
        self._capture_barrier = None
        self._capturing = set()
        return out

    def _start_capture(self, b: CheckpointBarrier) -> GateEvent:
        """Barrier overtakes: fire now, capture the other channels'
        pre-barrier data as it arrives."""
        self.captured = []  # an aborted older capture's data is discarded
        self._capture_barrier = b
        self._capturing = {i for i in range(len(self.channels))
                           if i not in self._barrier_seen
                           and not self._ended[i]}
        self._pending_barrier = None
        self._barrier_seen.clear()
        self._blocked = [False] * len(self.channels)
        return GateEvent("barrier", b)

    def begin_capture(self, b: CheckpointBarrier) -> None:
        """Externally start capture for a barrier that arrived on a SIBLING
        gate (two-input unaligned checkpoints): every live channel of this
        gate is pre-barrier until its own barrier shows up."""
        if self._capture_barrier is not None \
                and self._capture_barrier.checkpoint_id >= b.checkpoint_id:
            return
        self.captured = []
        self._capture_barrier = b
        self._capturing = {i for i in range(len(self.channels))
                           if not self._ended[i]}
        self._pending_barrier = None
        self._barrier_seen.clear()
        self._blocked = [False] * len(self.channels)

    def convert_to_unaligned(self) -> Optional[GateEvent]:
        """Escalate a stalled aligned checkpoint (alignment timeout)."""
        if self._pending_barrier is None or self.capture_active:
            return None
        return self._start_capture(self._pending_barrier)

    # -- watermark valve (reference StatusWatermarkValve) ------------------
    def _recompute_watermark(self) -> Optional[Watermark]:
        live = [self._wm[i] for i in range(len(self.channels))
                if self._active[i] and not self._ended[i]]
        if not live:
            # all idle/ended: watermark driven by ended channels' final marks
            live = [self._wm[i] for i in range(len(self.channels))]
        combined = min(live) if live else MIN_TIMESTAMP
        if combined > self._combined_wm:
            self._combined_wm = combined
            return Watermark(combined)
        return None

    def all_ended(self) -> bool:
        return all(self._ended)

    @property
    def aligning(self) -> bool:
        return self._pending_barrier is not None

    def unblock_all(self) -> None:
        self._blocked = [False] * len(self.channels)
        self._pending_barrier = None
        self._barrier_seen.clear()

    def poll(self) -> Optional[GateEvent]:
        """Poll one event, fair round-robin over non-blocked channels.
        Returns None when nothing is available right now."""
        if (self.alignment_timeout_s > 0 and not self.unaligned
                and self._pending_barrier is not None
                and not self.capture_active
                and time.time() - self.alignment_start
                > self.alignment_timeout_s):
            ev = self.convert_to_unaligned()
            if ev is not None:
                return ev
        n = len(self.channels)
        for off in range(n):
            i = (self._rr + off) % n
            if self._blocked[i] or self._ended[i]:
                continue
            e = self.channels[i].poll()
            if e is None:
                continue
            self._rr = (i + 1) % n
            return self._classify(i, e)
        return None

    def _classify(self, i: int, e: Any) -> Optional[GateEvent]:
        if isinstance(e, RecordBatch):
            if self._capture_barrier is not None and i in self._capturing:
                # pre-barrier in-flight data rides with the checkpoint AND
                # is processed normally (reference ChannelStateWriter)
                self.captured.append(e)
            return GateEvent("batch", e, i)
        if isinstance(e, Watermark):
            self._wm[i] = max(self._wm[i], e.timestamp)
            self._active[i] = True
            wm = self._recompute_watermark()
            return GateEvent("watermark", wm, i) if wm else None
        if isinstance(e, WatermarkStatus):
            self._active[i] = e.active
            wm = self._recompute_watermark()
            return GateEvent("watermark", wm, i) if wm else \
                GateEvent("idle", e, i)
        if isinstance(e, CheckpointBarrier):
            return self._on_barrier(i, e)
        if isinstance(e, LatencyMarker):
            return GateEvent("latency", e, i)
        if isinstance(e, EndOfInput):
            self._ended[i] = True
            self._capturing.discard(i)  # nothing more to capture from it
            # an ended channel no longer holds back alignment
            if self._pending_barrier is not None:
                return self._check_alignment_complete()
            wm = self._recompute_watermark()
            return GateEvent("watermark", wm, i) if wm else None
        raise TypeError(f"Unknown stream element {type(e)}")

    def _on_barrier(self, i: int, b: CheckpointBarrier) -> Optional[GateEvent]:
        if self._capture_barrier is not None:
            if b.checkpoint_id <= self._capture_barrier.checkpoint_id:
                # this channel caught up to the overtaking barrier
                self._capturing.discard(i)
                return None
            # a newer checkpoint while capturing (max_concurrent > 1):
            # finish the old capture forcibly and overtake again
            self._capturing.clear()
            self._barrier_seen = {i}
            return self._start_capture(b)
        if self.unaligned:
            self._barrier_seen.add(i)
            return self._start_capture(b)
        if not self.aligned:
            # at-least-once: CheckpointBarrierTracker — count, never block
            self._barrier_seen.add(i)
            if self._pending_barrier is None:
                self._pending_barrier = b
                self.alignment_start = time.time()
            return self._check_alignment_complete()
        if self._pending_barrier is None:
            self._pending_barrier = b
            self.alignment_start = time.time()
        elif b.checkpoint_id != self._pending_barrier.checkpoint_id:
            # new checkpoint overtakes: abort old alignment (reference
            # handles via abort; we adopt the newer barrier)
            self.unblock_all()
            self._pending_barrier = b
            self.alignment_start = time.time()
        self._blocked[i] = True
        self._barrier_seen.add(i)
        return self._check_alignment_complete()

    def _check_alignment_complete(self) -> Optional[GateEvent]:
        needed = {i for i in range(len(self.channels)) if not self._ended[i]}
        if self._pending_barrier is not None and needed <= self._barrier_seen:
            b = self._pending_barrier
            self.unblock_all()
            return GateEvent("barrier", b)
        return None


class IterationGate(InputGate):
    """Gate for an iteration head (reference StreamIterationHead): some
    channels are FEEDBACK edges from the loop body. Termination cannot wait
    for their EndOfInput — the body only ends after the head does — so the
    head ends once every regular channel ended AND the loop has been quiet
    (no event polled, no feedback data queued) for ``max_wait_s``. Feedback
    channels start inactive so the loop's (filtered-out) watermarks never
    hold back event time; only record batches flow on them."""

    def __init__(self, channels: list[Channel], feedback: set[int],
                 max_wait_s: float, **kwargs):
        super().__init__(channels, **kwargs)
        self.feedback = set(feedback)
        self.max_wait_s = max_wait_s
        self._quiet_since: Optional[float] = None
        self._regular = [i for i in range(len(channels))
                         if i not in self.feedback]
        for i in self.feedback:
            self._active[i] = False

    def poll(self) -> Optional[GateEvent]:
        ev = super().poll()
        if ev is not None:
            self._quiet_since = None     # any activity resets quiescence
        return ev

    def all_ended(self) -> bool:
        if not all(self._ended[i] for i in self._regular):
            self._quiet_since = None
            return False
        if all(self._ended):
            return True
        if any(self.channels[i].size() > 0 for i in self.feedback
               if not self._ended[i]):
            self._quiet_since = None     # queued feedback: not quiet
            return False
        now = time.time()
        if self._quiet_since is None:
            self._quiet_since = now
            return False
        return now - self._quiet_since >= self.max_wait_s
