"""Device slice-window operator: the north-star TPU execution path.

Replaces the reference's per-record window hot loop
(WindowOperator.processElement:278 + slice-shared table path
SliceSharedWindowAggProcessor) with whole-batch device execution:

* each micro-batch runs ONE compiled step — hash keys -> device hash-table
  slot resolution -> pane index -> one scatter-fold per aggregate into a
  [ring, capacity] pane accumulator (the slice decomposition of §5.7b:
  sliding windows never aggregate a record twice);
* there are NO per-key timers: a window ending at pane boundary ``p_end``
  fires when the (host-scalar) watermark passes ``p_end*pane - 1``, and the
  fire is one pane-merge reduction over all keys in the subtask's key-group
  range (BASELINE north star), after which the retired pane's ring row is
  zeroed for reuse;
* under shard_map the identical step runs per device on its key-group shard
  (keys are partitioned, so keyed aggregation needs no collective; global
  post-aggregations psum — see parallel/).

Late records (pane already fired) are dropped and counted, matching the host
operator at allowed_lateness=0; use the host WindowOperator for lateness
re-firing or merging windows.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.elements import Watermark
from ...core.records import MIN_TIMESTAMP, RecordBatch, Schema
from ...ops.hash_table import EMPTY_KEY
from ...ops.segment_ops import pane_window_merge
from ...state.tpu_backend import TpuKeyedStateBackend
from ...window.assigners import WindowAssigner
from .base import OneInputOperator, OperatorContext, Output
from .slice_control import SliceControlPlane

__all__ = ["DeviceWindowAggOperator", "AggSpec"]


class AggSpec:
    """One aggregate column: kind in sum|count|min|max|avg over field."""

    def __init__(self, kind: str, field: Optional[str] = None,
                 out_name: Optional[str] = None, dtype=jnp.float32):
        if kind not in ("sum", "count", "min", "max", "avg"):
            raise ValueError(f"unsupported device aggregate {kind}")
        self.kind = kind
        self.field = field
        self.out_name = out_name or (f"{kind}_{field}" if field else kind)
        self.dtype = dtype


class DeviceWindowAggOperator(SliceControlPlane, OneInputOperator):
    def __init__(self, assigner: WindowAssigner, key_column: str,
                 aggs: Sequence[AggSpec],
                 capacity: int = 1 << 16,
                 ring_size: int = 64,
                 emit_window_bounds: bool = True,
                 name: str = "DeviceWindowAgg"):
        super().__init__(name)
        pane = assigner.pane_size
        if pane is None:
            raise ValueError(
                "Device window operator needs a pane-decomposable assigner "
                "(tumbling, or sliding with size % slide == 0)")
        self._assigner = assigner
        self._pane = int(pane)
        self._offset = int(getattr(assigner, "offset", 0))
        size = getattr(assigner, "size", self._pane)
        self._window_panes = int(size) // self._pane  # W panes per window
        self._ring = int(ring_size)
        if self._ring < self._window_panes + 1:
            raise ValueError("ring_size must exceed panes per window")
        self._key_column = key_column
        self._aggs = list(aggs)
        self._capacity = capacity
        self._emit_bounds = emit_window_bounds

        self._backend: Optional[TpuKeyedStateBackend] = None
        self._init_control_plane()
        self._out_schema: Optional[Schema] = None

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        self._backend = TpuKeyedStateBackend(
            ctx.key_group_range, ctx.max_parallelism, capacity=self._capacity)
        self._backend.register_array_state("__count__", "count", jnp.int64,
                                           ring=self._ring)
        self._registered = False

    def _register_aggs(self, schema: Schema) -> None:
        """Accumulator dtypes follow the input columns (sum over int64
        accumulates int64, matching the host operator's Python arithmetic);
        avg always accumulates float."""
        for a in self._aggs:
            if a.field is not None and a.field in schema:
                col_dtype = np.dtype(schema.field(a.field).dtype)
                a.dtype = (jnp.float32 if a.kind == "avg"
                           else jnp.dtype(col_dtype))
            if a.kind == "avg":
                self._backend.register_array_state(
                    f"{a.out_name}.sum", "sum", a.dtype, ring=self._ring)
            elif a.kind != "count":
                self._backend.register_array_state(
                    a.out_name, a.kind, a.dtype, ring=self._ring)
        self._registered = True

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        if keyed_snapshots:
            self._backend.restore([s["backend"] for s in keyed_snapshots])
            self._restore_control_meta([s["meta"] for s in keyed_snapshots])
            # checkpoints taken under a different ring size re-seat their
            # live pane rows onto this operator's ring
            first = self._min_seen_pane
            if first is not None and self._fired_boundary is not None:
                first = max(first, self._fired_boundary - self._window_panes)
            live = (range(first, self._max_seen_pane + 1)
                    if first is not None else range(0))
            self._backend.conform_ring(self._ring, live)

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if not self._registered:
            key_dtype = batch.schema.field(self._key_column).dtype
            if key_dtype is object or not np.issubdtype(np.dtype(key_dtype),
                                                        np.integer):
                raise TypeError(
                    f"device window aggregation needs an integer key column; "
                    f"{self._key_column!r} is {key_dtype} — use the hashmap "
                    "state backend for float/string keys")
            self._register_aggs(batch.schema)
        keys = batch.column(self._key_column).astype(np.int64)
        self._ingest(batch, keys)

    def _fold(self, batch: RecordBatch, keys: np.ndarray,
              panes: np.ndarray) -> None:
        slots = self._backend.slots_for_batch(keys)
        ring_idx = jnp.asarray(panes % self._ring)
        valid = slots >= 0
        self._backend.fold_batch("__count__", slots,
                                 jnp.ones(batch.n, jnp.int64), valid,
                                 ring_idx=ring_idx)
        for a in self._aggs:
            if a.kind == "count":
                continue
            col = jnp.asarray(batch.column(a.field))
            name = f"{a.out_name}.sum" if a.kind == "avg" else a.out_name
            self._backend.fold_batch(name, slots, col, valid,
                                     ring_idx=ring_idx)

    # -- firing (fire loop lives in SliceControlPlane) ----------------------
    def _fire(self, p_end: int) -> None:
        W = self._window_panes
        # never read panes below min_seen: they hold no data and their ring
        # rows may be occupied by live FUTURE panes (row aliasing)
        first = max(p_end - W, self._min_seen_pane)
        if first >= p_end:
            return
        pane_rows = np.array([(p % self._ring) for p in range(first, p_end)],
                             dtype=np.int32)
        rows_d = jnp.asarray(pane_rows)
        count = pane_window_merge("count", self._backend.get_array("__count__"),
                                  rows_d)
        emit_mask = (self._backend.occupied_mask()) & (count > 0)
        results = {}
        for a in self._aggs:
            if a.kind == "count":
                results[a.out_name] = count
            elif a.kind == "avg":
                s = pane_window_merge(
                    "sum", self._backend.get_array(f"{a.out_name}.sum"), rows_d)
                results[a.out_name] = s / jnp.maximum(count, 1).astype(s.dtype)
            else:
                results[a.out_name] = pane_window_merge(
                    a.kind, self._backend.get_array(a.out_name), rows_d)

        self._emit(p_end, emit_mask, results)

        # retire the oldest pane of this window: no future window needs it
        # (skip panes below min_seen — their ring rows belong to live panes)
        if p_end - W >= self._min_seen_pane:
            self._backend.reset_ring_row((p_end - W) % self._ring)

    def _emit(self, p_end: int, emit_mask: jax.Array,
              results: dict[str, jax.Array]) -> None:
        mask = np.asarray(jax.device_get(emit_mask))
        if not mask.any():
            return
        idx = np.flatnonzero(mask)
        table = np.asarray(jax.device_get(self._backend.table))
        keys = table[idx]
        start = (p_end - self._window_panes) * self._pane + self._offset
        end = p_end * self._pane + self._offset
        cols: dict[str, np.ndarray] = {self._key_column: keys}
        fields: list[tuple[str, Any]] = [(self._key_column, np.int64)]
        if self._emit_bounds:
            cols["window_start"] = np.full(len(idx), start, np.int64)
            cols["window_end"] = np.full(len(idx), end, np.int64)
            fields += [("window_start", np.int64), ("window_end", np.int64)]
        for name, arr in results.items():
            vals = np.asarray(jax.device_get(arr))[idx]
            cols[name] = vals
            fields.append((name, vals.dtype.type))
        schema = Schema(fields)
        ts = np.full(len(idx), end - 1, np.int64)
        self.output.emit(RecordBatch(schema, cols, ts))

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": self._backend.snapshot(checkpoint_id),
                          "meta": self._control_meta()}}
