"""Device slice-window operator: the north-star TPU execution path.

Replaces the reference's per-record window hot loop
(WindowOperator.processElement:278 + slice-shared table path
SliceSharedWindowAggProcessor) with whole-batch device execution:

* each micro-batch runs ONE compiled step — hash keys -> device hash-table
  slot resolution -> pane index -> one scatter-fold per aggregate into a
  [ring, capacity] pane accumulator (the slice decomposition of §5.7b:
  sliding windows never aggregate a record twice);
* there are NO per-key timers: a window ending at pane boundary ``p_end``
  fires when the (host-scalar) watermark passes ``p_end*pane - 1``, and the
  fire is one pane-merge reduction over all keys in the subtask's key-group
  range (BASELINE north star), after which the retired pane's ring row is
  zeroed for reuse;
* under shard_map the identical step runs per device on its key-group shard
  (keys are partitioned, so keyed aggregation needs no collective; global
  post-aggregations psum — see parallel/).

Late records (pane already fired) are dropped and counted, matching the host
operator at allowed_lateness=0; use the host WindowOperator for lateness
re-firing or merging windows.
"""

from __future__ import annotations

import time
from collections import deque
from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.device_records import DeviceRecordBatch
from ...core.elements import Watermark
from ...core.records import MIN_TIMESTAMP, RecordBatch, Schema
from ...metrics.device import DEVICE_STATS, instrumented_program_cache, \
    pytree_nbytes
from ..faults import DeviceGuard, DeviceSegmentError, FAULTS, \
    fire_with_retries
from ..watchdog import WATCHDOG, stall_bounded
from ...ops.hash_table import EMPTY_KEY, lookup_or_insert, \
    sanitize_keys_device
from ...state.tpu_backend import TpuKeyedStateBackend
from ...window.assigners import WindowAssigner
from .base import OneInputOperator, OperatorContext, Output
from .slice_control import AsyncFireQueue, CoalescingIngest, \
    SliceControlPlane

__all__ = ["DeviceWindowAggOperator", "AggSpec"]


class AggSpec:
    """One aggregate column: kind in sum|count|min|max|avg over field.

    ``value_bits``: static bound on the aggregate's RESULT domain (non-
    negative, below 2^value_bits), used to shorten the top-k radix select
    at fire time (ops/topk.py) — each 16 bits saved drops one O(capacity)
    histogram pass. Defaults: 48 for count (exact up to 2.8e14 events per
    key per window), 64 (always safe) otherwise."""

    def __init__(self, kind: str, field: Optional[str] = None,
                 out_name: Optional[str] = None, dtype=jnp.float32,
                 value_bits: Optional[int] = None):
        if kind not in ("sum", "count", "min", "max", "avg"):
            raise ValueError(f"unsupported device aggregate {kind}")
        self.kind = kind
        self.field = field
        self.out_name = out_name or (f"{kind}_{field}" if field else kind)
        self.dtype = dtype
        self.value_bits = (value_bits if value_bits is not None
                           else 48 if kind == "count" else 64)


from ...ops.topk import masked_topk as _masked_topk  # noqa: E402
# exact radix-select top-k: XLA's sort-based lax.top_k over a [capacity]
# accumulator measured ~480 ms/fire (k=1000, 2M slots, CPU) and dominated
# the whole window-fire stage; radix select is O(capacity) histogram
# passes (see ops/topk.py)


def _step_body(fold_sig: tuple, ring: int, pane: int, offset: int,
               dirty_block: int, spill_maxp: int = 0):
    """The UNJITTED ingest-step body — pane assignment + late masking +
    hash-table lookup-or-insert + every scatter-fold. ``_step_program``
    wraps it in a donated jit (the standalone per-batch dispatch); the
    fused-chain lowering (runtime/compiled.py) composes it with the
    source decode under ONE jit instead, so the certified
    source→window prefix is a single XLA dispatch."""
    from ...ops.segment_ops import scatter_fold

    spill = spill_maxp > 0

    def step_fn(table, arrays, dropped, late, dirty, stage, touch, keys, ts,
                cols, spilled, batch_no, first_open, n_valid):
        panes = (ts.astype(jnp.int64) - offset) // pane
        # rows at/after n_valid are power-of-two padding (constant shapes
        # keep ONE executable across variable upstream batch lengths, e.g.
        # behind a WHERE filter); they fold nothing and count nothing
        in_batch = jnp.arange(keys.shape[0]) < n_valid
        fresh = (panes >= first_open) & in_batch
        late = late + jnp.sum(~fresh & in_batch).astype(jnp.int64)
        keys = sanitize_keys_device(keys)
        if spill:
            from ...parallel.mesh import key_groups_device

            groups = key_groups_device(keys, spill_maxp)
            # padding rows must not touch the LRU clock (their zero key's
            # group would read permanently hot and pin residency)
            touch = touch.at[jnp.where(in_batch, groups, spill_maxp)].max(
                batch_no, mode="drop")
            sp = spilled[groups]
            table, slots, ok = lookup_or_insert(table, keys, fresh & ~sp)
            to_host = fresh & (sp | ~ok)
            S = stage["keys"].shape[0]
            base = stage["count"]
            pos = base + jnp.cumsum(to_host) - 1
            can = to_host & (pos < S)
            dropped = dropped + jnp.sum(to_host & ~can).astype(jnp.int64)
            widx = jnp.where(can, pos, S).astype(jnp.int64)
            stage = dict(stage)
            stage["keys"] = stage["keys"].at[widx].set(keys, mode="drop")
            stage["ring"] = stage["ring"].at[widx].set(
                (panes % ring).astype(jnp.int32), mode="drop")
            for _kind, name, field in fold_sig:
                stage[name] = stage[name].at[widx].set(
                    cols[field].astype(stage[name].dtype), mode="drop")
            stage["count"] = base + jnp.sum(to_host).astype(jnp.int64)
        else:
            table, slots, ok = lookup_or_insert(table, keys, fresh)
            dropped = dropped + jnp.sum(~ok & fresh).astype(jnp.int64)
        count = arrays["__count__"]
        cap = count.shape[1]
        # int64 flat index once ring*capacity could overflow int32 (tables
        # auto-grow by doubling; shapes are static so this is trace-time)
        idt = jnp.int64 if ring * cap > (1 << 31) - 1 else jnp.int32
        ring_idx = (panes % ring).astype(idt)
        flat = ring_idx * cap + jnp.maximum(slots, 0).astype(idt)
        out = dict(arrays)
        out["__count__"] = scatter_fold(
            "count", count.reshape(-1), flat,
            jnp.ones(keys.shape[0], count.dtype), ok).reshape(count.shape)
        for kind, name, field in fold_sig:
            arr = arrays[name]
            vals = cols[field].astype(arr.dtype)
            out[name] = scatter_fold(kind, arr.reshape(-1), flat, vals,
                                     ok).reshape(arr.shape)
        # incremental-snapshot capture: mark touched dirty blocks
        dirty = dirty.at[jnp.maximum(slots, 0) // dirty_block].set(True)
        # completion token: a fresh scalar buffer that is NEVER fed back
        # into a donated argument, so the host can block on it to bound
        # the in-flight backlog (every other output becomes a donated
        # input of the next step and would be a deleted buffer by then)
        token = late + dropped
        return table, out, dropped, late, dirty, stage, touch, token

    return step_fn


@instrumented_program_cache("device_window.step")
def _step_program(fold_sig: tuple, ring: int, pane: int, offset: int,
                  dirty_block: int, spill_maxp: int = 0):
    """ONE compiled program per batch for the device-resident ingest path
    (see ``_step_body`` for what runs inside), over columns that are
    ALREADY in HBM (DeviceRecordBatch). This is the whole per-batch hot
    loop in a single dispatch — the analog of the reference's record loop
    StreamTask.processInput:588 → WindowOperator.processElement:278,
    executed once per micro-batch with zero host<->device transfers.
    State buffers are donated so XLA updates them in place instead of
    copying [ring, capacity] arrays every batch.

    ``fold_sig`` is a tuple of (fold_kind, state_name, field). The count
    plane ("__count__") folds implicitly.

    ``spill_maxp`` > 0 enables the deferred-spill split (HBM budget +
    defer_overflow): records of spilled key groups — and failed inserts —
    are excluded from the device fold and compacted into the ``stage``
    buffers for the host tier, still with zero host syncs; the per-group
    LRU clock updates on device. Stage overflow (more rows than the
    staging capacity between watermarks) counts into ``dropped`` and
    fails loudly at the next health check.
    """
    donate = (0, 1, 2, 3, 4, 5, 6) if spill_maxp > 0 else (0, 1, 2, 3, 4)
    return partial(jax.jit, donate_argnums=donate)(
        _step_body(fold_sig, ring, pane, offset, dirty_block, spill_maxp))


@instrumented_program_cache("device_window.native_fold")
def _native_fold_program(fold_sig: tuple, dirty_block: int):
    """CPU-fallback companion of _step_program: slots come from the native
    host index (backend.native_slots), so this program is only the scatter
    folds + dirty marking, donated for in-place plane updates. Returns a
    fresh completion token for the in-flight backpressure window."""
    from ...ops.segment_ops import scatter_fold

    @partial(jax.jit, donate_argnums=(0, 1))
    def fold(arrays, dirty, flat, slots, valid, vals):
        count = arrays["__count__"]
        out = dict(arrays)
        out["__count__"] = scatter_fold(
            "count", count.reshape(-1), flat,
            jnp.ones(flat.shape[0], count.dtype), valid).reshape(count.shape)
        for i, (kind, name, _field) in enumerate(fold_sig):
            arr = arrays[name]
            out[name] = scatter_fold(kind, arr.reshape(-1), flat,
                                     vals[i].astype(arr.dtype),
                                     valid).reshape(arr.shape)
        dirty = dirty.at[jnp.maximum(slots, 0) // dirty_block].set(True)
        token = jnp.sum(valid.astype(jnp.int64))
        return out, dirty, token

    return fold


@instrumented_program_cache("device_window.fire")
def _fire_program(agg_sig: tuple, topk: Optional[int],
                  topk_value_bits: int = 64):
    """ONE compiled program per (aggregate signature, top-k) covering the
    whole fire: masked pane-row merge for every aggregate + emit mask +
    optional device top-k + health scalars. Module-level and cached so
    every operator instance with the same shape shares the executable —
    fire programs must never recompile per instance or per pane count
    (compiles can cost tens of seconds when the chip sits behind a
    tunnel). ``pane_rows`` is therefore PADDED to the window width with a
    validity mask instead of varying in shape."""
    from ...ops.segment_ops import AGG_INITS, AGG_MERGES

    @jax.jit
    def fire_fn(table, arrays, pane_rows, rows_valid, dropped):
        def merge(kind, arr):
            sub = arr[pane_rows]                        # [W, cap]
            ident = AGG_INITS[kind](arr.dtype)
            sub = jnp.where(rows_valid[:, None], sub, ident)
            return AGG_MERGES[kind](sub, axis=0)

        def merge_at(kind, arr, idx):
            # winner-only merge: ONE [W, k] two-axis gather instead of a
            # full [W, capacity] pane merge — with emit_topk only k slots
            # ever emit, so secondary aggregates never pay the
            # full-capacity read. (NOT arr[pane_rows][:, idx]: the
            # chained form materializes the [W, cap] intermediate.)
            sub = arr[pane_rows[:, None], idx[None, :]]
            ident = AGG_INITS[kind](arr.dtype)
            sub = jnp.where(rows_valid[:, None], sub, ident)
            return AGG_MERGES[kind](sub, axis=0)

        count = merge("count", arrays["__count__"])
        emit = (table != jnp.int64(EMPTY_KEY)) & (count > 0)
        occ = (table != jnp.int64(EMPTY_KEY)).sum()
        if topk is not None:
            # rank on the FIRST aggregate; everything else gathers at the
            # k winners only
            rk_kind, rk_name = agg_sig[0]
            if rk_kind == "count":
                ranked = count
            elif rk_kind == "avg":
                s = merge("sum", arrays[f"{rk_name}.sum"])
                ranked = s / jnp.maximum(count, 1).astype(s.dtype)
            else:
                ranked = merge(rk_kind, arrays[rk_name])
            _vals, idx, ok = _masked_topk(ranked, emit, topk,
                                          value_bits=topk_value_bits)
            keys = jnp.take(table, idx)
            count_k = jnp.take(count, idx)
            out = {}
            for kind, out_name in agg_sig:
                if out_name == rk_name:
                    out[out_name] = jnp.take(ranked, idx)
                elif kind == "count":
                    out[out_name] = count_k
                elif kind == "avg":
                    s = merge_at("sum", arrays[f"{out_name}.sum"], idx)
                    out[out_name] = s / jnp.maximum(count_k, 1).astype(
                        s.dtype)
                else:
                    out[out_name] = merge_at(kind, arrays[out_name], idx)
            return keys, ok, out, dropped, occ
        results = {}
        for kind, out_name in agg_sig:
            if kind == "count":
                results[out_name] = count
            elif kind == "avg":
                s = merge("sum", arrays[f"{out_name}.sum"])
                results[out_name] = s / jnp.maximum(count, 1).astype(s.dtype)
            else:
                results[out_name] = merge(kind, arrays[out_name])
        return table, emit, results, dropped, occ

    return fire_fn


@instrumented_program_cache("device_window.seal")
def _seal_program(inv_sig: tuple, tree_sig: tuple):
    """Incremental fire engine, steady-state path: ONE donated program per
    aggregate signature that seals the newest pane into the running window
    state and yields this fire's merged view — O(capacity) (invertible) /
    O(capacity·log ring) (merge tree) regardless of window width.

    * invertible aggregates (``inv_sig``: sum/count/avg-sum) keep a
      [capacity] running accumulator: fire view = acc + sealed pane row;
      the next state subtracts the retiring pane (masked when that pane
      predates the data: its ring row may alias a live future pane);
    * non-invertible aggregates (``tree_sig``: min/max) keep a heap-
      ordered binary merge tree over per-pane leaf copies: clear the
      retiring leaf, write the sealed pane's leaf, recompute both ancestor
      paths (O(log) dynamic row updates); the fire view is the root.

    The pane planes (``arrays``) are read BEFORE the caller retires the
    oldest ring row, so the subtraction sees the retiring pane intact.
    Leaf/row indices are traced scalars — one executable serves every
    pane, and none of the shapes depend on the window width W (the tree
    is sized by the ring), so seal programs are shared across window
    configurations."""
    from ...ops.segment_ops import AGG_COMBINE2, AGG_INITS, AGG_INVERT, \
        merge_tree_update

    @partial(jax.jit, donate_argnums=(1, 2))
    def seal_fn(arrays, wins, trees, new_row, sub_row, sub_valid,
                new_leaf, old_leaf):
        view, new_wins, new_trees = {}, {}, {}
        for kind, name in inv_sig:
            arr = arrays[name]
            new_pane = jax.lax.dynamic_index_in_dim(arr, new_row, 0,
                                                    keepdims=False)
            fire_v = AGG_COMBINE2[kind](wins[name], new_pane)
            sub_pane = jax.lax.dynamic_index_in_dim(arr, sub_row, 0,
                                                    keepdims=False)
            retire = jnp.where(sub_valid, sub_pane,
                               AGG_INITS[kind](arr.dtype))
            view[name] = fire_v
            new_wins[name] = AGG_INVERT[kind](fire_v, retire)
        for kind, name in tree_sig:
            arr = arrays[name]
            ident = jnp.full(arr.shape[1:], AGG_INITS[kind](arr.dtype),
                             arr.dtype)
            # clear the retiring pane's leaf FIRST: its position can never
            # be a live pane's (any two live panes differ by < tree size)
            tree = merge_tree_update(kind, trees[name], old_leaf, ident)
            new_pane = jax.lax.dynamic_index_in_dim(arr, new_row, 0,
                                                    keepdims=False)
            tree = merge_tree_update(kind, tree, new_leaf, new_pane)
            view[name] = tree[1]
            new_trees[name] = tree
        return view, new_wins, new_trees

    return seal_fn


@instrumented_program_cache("device_window.fire_rebuild")
def _rebuild_program(inv_sig: tuple, tree_sig: tuple, tree_size: int):
    """Incremental fire engine, recovery path: rebuild the running window
    state from the pane planes in one dispatch — after restore/degrade, a
    fire-boundary jump, or a write into an already-sealed pane. Reads the
    live window's pane rows exactly like the full merge (pane_rows is
    padded to the RING length with a validity mask so the program shape
    stays W-independent) and returns this fire's view plus consistent
    next-state accumulators/trees."""
    from ...ops.segment_ops import AGG_INITS, AGG_INVERT, AGG_MERGES, \
        merge_tree_build

    L = tree_size

    @jax.jit
    def rebuild_fn(arrays, pane_rows, rows_valid, pane_leaves, sub_row,
                   sub_valid):
        view, new_wins, new_trees = {}, {}, {}
        for kind, name in inv_sig:
            arr = arrays[name]
            ident = AGG_INITS[kind](arr.dtype)
            sub = jnp.where(rows_valid[:, None], arr[pane_rows], ident)
            fire_v = AGG_MERGES[kind](sub, axis=0)
            view[name] = fire_v
            sub_pane = jax.lax.dynamic_index_in_dim(arr, sub_row, 0,
                                                    keepdims=False)
            retire = jnp.where(sub_valid, sub_pane, ident)
            new_wins[name] = AGG_INVERT[kind](fire_v, retire)
        for kind, name in tree_sig:
            arr = arrays[name]
            ident = AGG_INITS[kind](arr.dtype)
            rows = jnp.where(rows_valid[:, None], arr[pane_rows], ident)
            leaves = jnp.full((L,) + arr.shape[1:], ident, arr.dtype)
            lidx = jnp.where(rows_valid, pane_leaves, L)
            leaves = leaves.at[lidx].set(rows, mode="drop")
            tree = merge_tree_build(kind, leaves)
            view[name] = tree[1]
            new_trees[name] = tree
        return view, new_wins, new_trees

    return rebuild_fn


@instrumented_program_cache("device_window.fire_inc")
def _fire_inc_program(agg_sig: tuple, topk: Optional[int],
                      topk_value_bits: int = 64):
    """Incremental counterpart of ``_fire_program``: identical outputs
    (emit mask / top-k, health scalars), but every aggregate's window
    merge is a [capacity] READ of the sealed view — no [W, capacity] pane
    gather anywhere. The signature carries no window width, so one
    executable serves every W."""

    @jax.jit
    def fire_fn(table, view, dropped):
        count = view["__count__"]
        emit = (table != jnp.int64(EMPTY_KEY)) & (count > 0)
        occ = (table != jnp.int64(EMPTY_KEY)).sum()
        if topk is not None:
            rk_kind, rk_name = agg_sig[0]
            if rk_kind == "count":
                ranked = count
            elif rk_kind == "avg":
                s = view[f"{rk_name}.sum"]
                ranked = s / jnp.maximum(count, 1).astype(s.dtype)
            else:
                ranked = view[rk_name]
            _vals, idx, ok = _masked_topk(ranked, emit, topk,
                                          value_bits=topk_value_bits)
            keys = jnp.take(table, idx)
            count_k = jnp.take(count, idx)
            out = {}
            for kind, out_name in agg_sig:
                if out_name == rk_name:
                    out[out_name] = jnp.take(ranked, idx)
                elif kind == "count":
                    out[out_name] = count_k
                elif kind == "avg":
                    s = jnp.take(view[f"{out_name}.sum"], idx)
                    out[out_name] = s / jnp.maximum(count_k, 1).astype(
                        s.dtype)
                else:
                    out[out_name] = jnp.take(view[out_name], idx)
            return keys, ok, out, dropped, occ
        results = {}
        for kind, out_name in agg_sig:
            if kind == "count":
                results[out_name] = count
            elif kind == "avg":
                s = view[f"{out_name}.sum"]
                results[out_name] = s / jnp.maximum(count, 1).astype(s.dtype)
            else:
                results[out_name] = view[out_name]
        return table, emit, results, dropped, occ

    return fire_fn


class DeviceWindowAggOperator(AsyncFireQueue, CoalescingIngest,
                              SliceControlPlane, OneInputOperator):
    def __init__(self, assigner: WindowAssigner, key_column: str,
                 aggs: Sequence[AggSpec],
                 capacity: int = 1 << 16,
                 ring_size: int = 64,
                 emit_window_bounds: bool = True,
                 emit_topk: Optional[int] = None,
                 defer_overflow: bool = False,
                 async_fire: bool = False,
                 hbm_budget_slots: int = 0,
                 spill_staging_slots: int = 1 << 16,
                 fire_incremental: Optional[bool] = None,
                 name: str = "DeviceWindowAgg"):
        """``emit_topk``: emit only the k keys with the largest value of the
        FIRST aggregate per window (one device lax.top_k instead of a full
        [capacity] host materialization) — the Nexmark Q5 hot-items /
        ORDER BY ... LIMIT k fire shape.

        ``defer_overflow``: never sync the hot path with the host; hash
        overflow accumulates in a device counter checked at fire time.
        ``async_fire``: fire programs emit asynchronously — results are
        drained once their device->host copy lands, and watermarks are
        held behind their fires. Both default off (fully synchronous
        semantics); the benchmark/production path enables both."""
        super().__init__(name)
        pane = assigner.pane_size
        if pane is None:
            raise ValueError(
                "Device window operator needs a pane-decomposable assigner "
                "(tumbling, or sliding with size % slide == 0)")
        from ...window.assigners import reject_variable_pane_assigner
        reject_variable_pane_assigner(assigner, "device")
        self._assigner = assigner
        self._pane = int(pane)
        self._offset = int(getattr(assigner, "offset", 0))
        size = getattr(assigner, "size", self._pane)
        self._window_panes = int(size) // self._pane  # W panes per window
        self._ring = int(ring_size)
        if self._ring < self._window_panes + 1:
            raise ValueError("ring_size must exceed panes per window")
        self._key_column = key_column
        self._aggs = list(aggs)
        self._capacity = capacity
        self._emit_bounds = emit_window_bounds
        self._topk = emit_topk
        self._defer = bool(defer_overflow)
        self._async = bool(async_fire)
        self._hbm_budget = int(hbm_budget_slots)
        self._stage_slots = int(spill_staging_slots)
        self._stage = None  # deferred-spill staging buffers (device)

        self._backend: Optional[TpuKeyedStateBackend] = None
        self._init_control_plane()
        if self._async:
            self._record_fire_latency = False
        self._init_async_fires()
        # bounded in-flight window: the host thread can dispatch an entire
        # bounded stream into the device queue before the first program
        # retires, which pushes every queued fire's completion (and its
        # latency) to the end of the run. Holding a small deque of step
        # outputs and blocking on the (k-2)th before admitting batch k
        # keeps the device fed while capping the backlog — p99 fire
        # latency then tracks the per-batch service time instead of the
        # job tail.
        self._inflight: deque = deque()
        self._max_inflight = 2  # overridable via task.max-inflight (setup)
        self._fire_fn = None
        self._out_schema: Optional[Schema] = None
        self._late_dev = None  # device late-drop counter (device ingest)
        self._late_cached = 0  # host cache of _late_dev (metrics scrapes
        # must never force a device sync; refreshed at fire/checkpoint
        # boundaries)
        # incremental fire engine (window.fire.incremental): running
        # window accumulator per invertible aggregate + merge tree per
        # min/max aggregate, updated once per pane seal. _inc_next is the
        # fire boundary the sealed state is consistent FOR; _inc_dirty
        # forces a one-dispatch rebuild from the pane planes (restore,
        # degrade, boundary jump, write into a sealed pane).
        self._inc_flag = fire_incremental
        self._inc_enabled = bool(fire_incremental)
        self._inc_next: Optional[int] = None
        self._inc_dirty = True
        from ...ops.segment_ops import pow2_ceil
        self._tree_size = pow2_ceil(self._ring)  # leaf count L (>= ring)
        self._init_coalescer()
        # degradation ladder (docs/ROBUSTNESS.md): once a persistent
        # compiled-segment failure evacuates state to host, this operator
        # is pinned to the CPU-fallback ingest path for its lifetime
        self._degraded = False
        self._degrade_enabled = True
        self._validate_batches = False
        self._guard: Optional[DeviceGuard] = None
        self.quarantined_batches = 0
        # certified fused-chain lowering (graph/fusion.py lowered_prefix):
        # armed by the deployer via enable_fused_chain, built lazily once
        # aggregate dtypes are known
        self._fused_spec = None     # (source, subtask, parallelism)
        self._fused_chain = None    # runtime.compiled.FusedChain
        # wall-clock per hot-path stage (bench breakdown): ingest = pack +
        # upload + fold dispatch, fire = fire dispatch, drain = result
        # materialization + emit
        self.stage_s: dict[str, float] = {"ingest": 0.0, "fire": 0.0,
                                          "drain": 0.0}

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        from ...core.config import FaultOptions, StateOptions, TaskOptions, \
            WindowOptions
        budget = self._hbm_budget or ctx.config.get(
            StateOptions.TPU_HBM_BUDGET)
        if not budget:
            # byte-denominated budget: convert to slots from the per-slot
            # footprint this operator allocates — the 8-byte table key
            # plus every [ring, capacity] accumulator plane row (count
            # plane + one value plane per non-count aggregate, avg's sum
            # plane included), at 8 bytes per cell (the widest dtype the
            # planes use; narrower dtypes just land under budget)
            budget_bytes = int(ctx.config.get(
                StateOptions.TPU_HBM_BUDGET_BYTES) or 0)
            if budget_bytes:
                value_planes = sum(1 for a in self._aggs
                                   if a.kind != "count")
                slot_bytes = 8 + (self._ring or 1) * 8 * (1 + value_planes)
                budget = max(1, budget_bytes // slot_bytes)
        if self._inc_flag is None:
            self._inc_enabled = bool(
                ctx.config.get(WindowOptions.FIRE_INCREMENTAL))
        self._max_inflight = max(1, int(
            ctx.config.get(TaskOptions.MAX_INFLIGHT)))
        self._coalesce_target = int(
            ctx.config.get(TaskOptions.COALESCE_TARGET_RECORDS))
        self._coalesce_timeout_s = float(
            ctx.config.get(TaskOptions.COALESCE_TIMEOUT_MS)) / 1e3
        self._guard = DeviceGuard("device_window", ctx.config)
        self._degrade_enabled = bool(
            ctx.config.get(FaultOptions.DEGRADATION))
        self._validate_batches = bool(
            ctx.config.get(FaultOptions.VALIDATE_BATCHES))
        # fused chains insert through the XLA probe inside the composed
        # program; mixing the native host index's slot assignment with
        # XLA probing on one table would place a key at two slots, so a
        # certified chain forces the device index on
        host_index = (bool(ctx.config.get(StateOptions.TPU_HOST_INDEX))
                      and self._fused_spec is None)
        self._backend = TpuKeyedStateBackend(
            ctx.key_group_range, ctx.max_parallelism,
            capacity=self._capacity, config=ctx.config,
            defer_overflow=self._defer,
            hbm_budget_slots=budget, host_index=host_index)
        if self._backend.tiering_active:
            from ...state.tiering import register_residency
            register_residency(
                f"{ctx.task_name}/{ctx.subtask_index}",
                self._backend.residency)
        # count-plane width follows the declared result bound: a COUNT
        # aggregate with value_bits <= 31 promises every per-window count
        # fits int32, which halves the fold scatter + fire merge traffic
        # on the [ring, capacity] plane (the whole-capacity passes are the
        # memory-bound cost at 10M+ keys) and feeds the uint32 radix
        # select directly
        cvb = min((a.value_bits for a in self._aggs if a.kind == "count"),
                  default=64)
        count_dtype = jnp.int32 if cvb <= 31 else jnp.int64
        self._backend.register_array_state("__count__", "count", count_dtype,
                                           ring=self._ring)
        self._registered = False

    def enable_fused_chain(self, source, subtask: int,
                           parallelism: int) -> bool:
        """Arm the certified source→window lowering (called by the
        deployer when the job's FusionCertificate carries a
        ``lowered_prefix`` for this vertex, BEFORE setup). The upstream
        reader then emits ``LazyDeviceBatch`` handles and this operator
        folds each with one composed decode+step dispatch. Only legal
        under deferred-overflow semantics — the composed program checks
        nothing synchronously, exactly like ``_ingest_device``."""
        if not self._defer:
            return False
        self._fused_spec = (source, int(subtask), int(parallelism))
        return True

    def _register_aggs(self, schema: Schema) -> None:
        """Accumulator dtypes follow the input columns (sum over int64
        accumulates int64, matching the host operator's Python arithmetic);
        avg always accumulates float."""
        for a in self._aggs:
            if a.field is not None and a.field in schema:
                col_dtype = np.dtype(schema.field(a.field).dtype)
                a.dtype = (jnp.float32 if a.kind == "avg"
                           else jnp.dtype(col_dtype))
            if a.kind == "avg":
                self._backend.register_array_state(
                    f"{a.out_name}.sum", "sum", a.dtype, ring=self._ring)
            elif a.kind != "count":
                self._backend.register_array_state(
                    a.out_name, a.kind, a.dtype, ring=self._ring)
        self._registered = True

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        if keyed_snapshots:
            self._backend.restore([s["backend"] for s in keyed_snapshots])
            self._restore_control_meta([s["meta"] for s in keyed_snapshots])
            # checkpoints taken under a different ring size re-seat their
            # live pane rows onto this operator's ring
            first = self._min_seen_pane
            if first is not None and self._fired_boundary is not None:
                first = max(first, self._fired_boundary - self._window_panes)
            live = (range(first, self._max_seen_pane + 1)
                    if first is not None else range(0))
            self._backend.conform_ring(self._ring, live)
            # snapshots never carry the derived incremental state (full-
            # merge checkpoints restore into incremental mode and vice
            # versa): the first fire after restore rebuilds it
            self._inc_dirty = True
            self._inc_next = None

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if self._coalesce_target > 1:
            from ...core.device_records import LazyDeviceBatch
            if isinstance(batch, LazyDeviceBatch):
                # a lazy chain batch is already a full micro-batch; admit
                # it directly (flushing buffered host batches first keeps
                # arrival order)
                self._coalesce_flush()
            else:
                self._coalesce_admit(batch)
                return
        self._process_batch_now(batch)

    def _process_batch_now(self, batch: RecordBatch) -> None:
        if self._pending:
            self._drain(block=False)
        if batch.n == 0:
            return
        if not self._registered:
            key_dtype = batch.schema.field(self._key_column).dtype
            if key_dtype is object or not np.issubdtype(np.dtype(key_dtype),
                                                        np.integer):
                raise TypeError(
                    f"device window aggregation needs an integer key column; "
                    f"{self._key_column!r} is {key_dtype} — use the hashmap "
                    "state backend for float/string keys")
            self._register_aggs(batch.schema)
        if self._validate_batches:
            batch = self._screen_nonfinite(batch)
            if batch.n == 0:
                return
        t0 = time.perf_counter()
        from ...core.device_records import LazyDeviceBatch
        if (self._fused_spec is not None
                and isinstance(batch, LazyDeviceBatch)
                and batch._realized is None
                and not self._degraded
                and not self._backend.host_index_active
                and not self._spill_deferred):
            # certified fused chain: decode + fold in ONE dispatch; any
            # condition above failing lets the lazy batch realize through
            # the ordinary ladder below (graceful unfusing)
            self._ingest_chain(batch)
        elif self._degraded and not self._backend.host_index_active:
            # degradation ladder, last rung: state lives host-side, slot
            # resolution through the synchronous backend path; device
            # batches are viewed as host columns (on the CPU backend a
            # view, not a transfer)
            hb = self._host_view(batch)
            keys = np.asarray(hb.column(self._key_column)).astype(
                np.int64, copy=False)
            self._ingest(hb, keys)
        elif self._backend.host_index_active:
            # CPU fallback: slot resolution through the native host index
            # (the "device" IS the host — see TpuKeyedStateBackend
            # .native_slots); pane bookkeeping + late filter run in the
            # shared control plane, folds stay donated XLA programs
            hb = self._host_view(batch)
            keys = np.asarray(hb.column(self._key_column)).astype(
                np.int64, copy=False)
            self._ingest(hb, keys)
        elif (isinstance(batch, DeviceRecordBatch) and self._defer
                and batch.dtimestamps is not None):
            self._ingest_device(batch)
        elif self._spill_deferred:
            # deferred spill runs the fused device split for host batches
            # too: upload the needed columns and go through the one-dispatch
            # path (the staging compaction needs the device key groups)
            self._ingest_device(self._to_device_batch(batch))
        else:
            keys = batch.column(self._key_column).astype(np.int64)
            self._ingest(batch, keys)
        self.stage_s["ingest"] += time.perf_counter() - t0

    @property
    def _spill_deferred(self) -> bool:
        return (self._defer and self._backend is not None
                and self._backend.hbm_budget > 0)

    def _to_device_batch(self, batch: RecordBatch) -> DeviceRecordBatch:
        ts = batch.timestamps

        def upload():
            cols = {self._key_column: jnp.asarray(
                batch.column(self._key_column).astype(np.int64))}
            for a in self._aggs:
                if a.field is not None and a.field not in cols:
                    cols[a.field] = jnp.asarray(batch.column(a.field))
            return cols, jnp.asarray(ts)

        # deadline-bounded idempotent upload (pure function of host data:
        # a stall-abandoned attempt re-runs safely)
        cols, dts = stall_bounded("transfer.h2d", upload,
                                  scope="device_window")
        schema = Schema([(f.name, f.dtype) for f in batch.schema.fields
                         if f.name in cols])
        DEVICE_STATS.note_h2d(pytree_nbytes(cols) + dts.nbytes, batch.n)
        return DeviceRecordBatch(schema, cols, dts,
                                 int(ts.min()), int(ts.max()))

    # -- degradation ladder / dead-letter quarantine ------------------------
    def _screen_nonfinite(self, batch: RecordBatch) -> RecordBatch:
        """faults.validate-batches: rows carrying NaN/Inf in any
        aggregated float column are quarantined to the dead-letter output
        BEFORE folding — a NaN folded into a sum/avg plane poisons every
        later window of that key."""
        bad = None
        for a in self._aggs:
            if a.field is None:
                continue
            col = np.asarray(self._host_view(batch).column(a.field))
            if not np.issubdtype(col.dtype, np.floating):
                continue
            mask = ~np.isfinite(col)
            bad = mask if bad is None else (bad | mask)
        if bad is None or not bad.any():
            return batch
        hb = self._host_view(batch)
        self._dead_letter(hb.filter(bad))
        return hb.filter(~bad)

    def _dead_letter(self, batch: RecordBatch) -> None:
        """Quarantine a (host-viewed) batch: counted, side-emitted under
        the 'dead-letter' tag when a side output is wired, never folded."""
        DEVICE_STATS.note_dead_letter(batch.n)
        self.quarantined_batches += 1
        try:
            self.output.emit_side("dead-letter", batch)
        except NotImplementedError:
            pass  # no side output wired: the counter is the record

    def _degrade(self, cause: BaseException) -> None:
        """Persistent compiled-segment failure: evacuate device state to
        host through the existing snapshot path, rebuild the backend in
        its synchronous host-fallback configuration, and pin this
        operator to the CPU ingest path. Keyed state and the pane/fire
        metadata survive verbatim, so exactly-once results are preserved;
        the fault-injection sites stop firing for this operator (the
        fallback of last resort is never chaos-injected)."""
        if self._degraded:
            raise cause
        with FAULTS.suppressed():
            self._drain(block=True)
            while self._inflight:
                jax.block_until_ready(self._inflight.popleft())
            self._pre_fire_flush()
            snap = self._backend.snapshot(-1)
            if self._late_dev is not None:
                # lint: sync-ok degrade path: final drain of the device counter, once per degrade
                self._late_dropped += int(jax.device_get(self._late_dev))
                self._late_dev = None
                self._late_cached = 0
            from ...core.config import StateOptions
            new_backend = TpuKeyedStateBackend(
                self.ctx.key_group_range, self.ctx.max_parallelism,
                capacity=self._capacity, defer_overflow=False,
                hbm_budget_slots=0,
                host_index=bool(self.ctx.config.get(
                    StateOptions.TPU_HOST_INDEX)))
            new_backend.restore([snap])
        if self._backend.tiering_active:
            # the fallback backend is unbudgeted: retire the residency
            # registry entry (and any queued prefetch staging) with it
            self._backend.prefetch_pipeline.cancel()
            from ...state.tiering import unregister_residency
            unregister_residency(
                f"{self.ctx.task_name}/{self.ctx.subtask_index}")
        self._backend = new_backend
        self._defer = False
        self._stage = None
        self._degraded = True
        self._guard.active = False
        # the evacuated snapshot carries only pane planes (window-role
        # state is derived): the next incremental fire rebuilds
        self._inc_dirty = True
        DEVICE_STATS.note_degraded("device_window")

    def _on_segment_failure(self, err: DeviceSegmentError,
                            batch=None) -> bool:
        """Shared escalation: poison faults quarantine the batch (returns
        True: handled, nothing folded); anything else degrades when
        allowed (returns False: caller re-runs through the fallback) or
        re-raises into task failover."""
        if err.poison and batch is not None:
            self._dead_letter(self._host_view(batch))
            return True
        if self._degrade_enabled and not self._degraded:
            self._degrade(err)
            return False
        raise err

    def _note_open_ingest(self, min_pane: int) -> None:
        """A write into a pane the incremental engine already sealed
        (pane < _inc_next - 1: late-but-open records or a min-pane
        decrease) invalidates the running window state; the next fire
        rebuilds it from the pane planes in one dispatch."""
        if self._inc_next is not None and min_pane < self._inc_next - 1:
            self._inc_dirty = True

    # -- device-resident ingest (zero-transfer hot path) --------------------
    def _fold_sig(self) -> tuple:
        sig = []
        for a in self._aggs:
            if a.kind == "count":
                continue
            name = f"{a.out_name}.sum" if a.kind == "avg" else a.out_name
            sig.append(("sum" if a.kind == "avg" else a.kind, name, a.field))
        return tuple(sig)

    def _ingest_device(self, batch: DeviceRecordBatch) -> None:
        """Whole-batch ingest of device-born columns: host does only pane
        bookkeeping on the batch's event-time BOUNDS; the data plane is one
        compiled dispatch (see _step_program). Late records are masked and
        counted on device; a batch wholly behind the fired boundary is
        dropped without any device work at all."""
        pane_lo = (batch.ts_min - self._offset) // self._pane
        pane_hi = (batch.ts_max - self._offset) // self._pane
        first_open = (self._fired_boundary - self._window_panes
                      if self._fired_boundary is not None else None)
        if first_open is not None and pane_hi < first_open:
            self._late_dropped += batch.n
            return
        eff_lo = pane_lo if first_open is None else max(pane_lo, first_open)
        self._max_seen_pane = (pane_hi if self._max_seen_pane is None
                               else max(self._max_seen_pane, pane_hi))
        self._min_seen_pane = (eff_lo if self._min_seen_pane is None
                               else min(self._min_seen_pane, eff_lo))
        self._note_open_ingest(eff_lo)
        low = (first_open if self._fired_boundary is not None
               else self._min_seen_pane)
        if pane_hi - low >= self._ring:
            raise RuntimeError(
                f"pane ring overflow: open span [{low},{pane_hi}] exceeds "
                f"ring {self._ring}; increase ring_size or reduce "
                "watermark lag")
        if self._late_dev is None:
            self._late_dev = jnp.zeros((), jnp.int64)
        spill = self._spill_deferred
        if spill and self._stage is None:
            self._alloc_stage()
        sig = self._fold_sig()
        fo = np.int64(first_open if first_open is not None else MIN_TIMESTAMP)

        def dispatch():
            step = _step_program(sig, self._ring, self._pane, self._offset,
                                 self._backend.dirty_block_size,
                                 self._backend.max_parallelism if spill
                                 else 0)
            arrays = {n: self._backend.get_array(n)
                      for n in self._fire_array_names()}
            from ...ops.segment_ops import pow2_ceil

            n = batch.n
            P = pow2_ceil(n)

            def _pad(a):
                return (a if P == n
                        else jnp.concatenate([a, jnp.zeros(P - n, a.dtype)]))

            cols = {f: _pad(batch.device_column(f)) for _k, _n, f in sig}
            return step(
                self._backend.table, arrays, self._backend.dropped_device,
                self._late_dev, self._backend.dirty_mask,
                self._stage if spill else None,
                self._backend.touch_device if spill else None,
                _pad(batch.device_column(self._key_column)),
                _pad(batch.dtimestamps), cols,
                self._backend.spilled_mask_device if spill else None,
                np.int64(self._backend.note_batch()) if spill
                else np.int64(0),
                fo, np.int64(n))

        try:
            table, new_arrays, dropped, late, dirty, stage, touch, token = \
                self._guard.run(dispatch)
        except DeviceSegmentError as e:
            if self._on_segment_failure(e, batch):
                return  # poisoned batch quarantined; state untouched
            # degraded mid-stream: this batch re-runs through the host
            # ingest path against the evacuated state (nothing folded
            # device-side — the fault fired before dispatch)
            hb = self._host_view(batch)
            keys = np.asarray(hb.column(self._key_column)).astype(
                np.int64, copy=False)
            self._ingest(hb, keys)
            return
        self._backend.table = table
        for n, a in new_arrays.items():
            self._backend.set_array(n, a)
        self._backend._dropped = dropped
        self._backend.set_dirty_mask(dirty)
        self._late_dev = late
        if spill:
            self._stage = stage
            self._backend.set_touch_device(touch)
        self._admit_token(token)

    def _ingest_chain(self, batch) -> None:
        """Certified-chain ingest: the batch is a ``LazyDeviceBatch`` —
        no columns exist yet. ONE composed program (runtime/compiled.py)
        decodes the batch from its start index and folds it into the
        donated window state; pane bookkeeping on the analytic bounds is
        identical to ``_ingest_device``."""
        pane_lo = (batch.ts_min - self._offset) // self._pane
        pane_hi = (batch.ts_max - self._offset) // self._pane
        first_open = (self._fired_boundary - self._window_panes
                      if self._fired_boundary is not None else None)
        if first_open is not None and pane_hi < first_open:
            # wholly late (contradicts the monotonic-source contract, so
            # effectively unreachable): realize so the reader's deferred
            # contract check still sees this batch's outputs
            batch.realize()
            self._late_dropped += batch.n
            return
        eff_lo = pane_lo if first_open is None else max(pane_lo, first_open)
        self._max_seen_pane = (pane_hi if self._max_seen_pane is None
                               else max(self._max_seen_pane, pane_hi))
        self._min_seen_pane = (eff_lo if self._min_seen_pane is None
                               else min(self._min_seen_pane, eff_lo))
        self._note_open_ingest(eff_lo)
        low = (first_open if self._fired_boundary is not None
               else self._min_seen_pane)
        if pane_hi - low >= self._ring:
            raise RuntimeError(
                f"pane ring overflow: open span [{low},{pane_hi}] exceeds "
                f"ring {self._ring}; increase ring_size or reduce "
                "watermark lag")
        if self._late_dev is None:
            self._late_dev = jnp.zeros((), jnp.int64)
        if self._fused_chain is None:
            from ..compiled import FusedChain
            source, subtask, parallelism = self._fused_spec
            self._fused_chain = FusedChain(
                source, subtask, parallelism, self._key_column,
                self._fold_sig(), self._ring, self._pane, self._offset,
                self._backend.dirty_block_size)
        chain = self._fused_chain
        fo = np.int64(first_open if first_open is not None else MIN_TIMESTAMP)

        def dispatch():
            arrays = {n: self._backend.get_array(n)
                      for n in self._fire_array_names()}
            return chain.run(batch.n, batch.start, batch.prev_last,
                             self._backend.table, arrays,
                             self._backend.dropped_device, self._late_dev,
                             self._backend.dirty_mask, fo)

        try:
            table, new_arrays, dropped, late, dirty, viol, last, token = \
                self._guard.run(dispatch)
        except DeviceSegmentError as e:
            if self._on_segment_failure(e, batch):
                return  # poisoned batch quarantined; state untouched
            # degraded mid-stream: re-run through the host path (realizes
            # the batch — nothing folded device-side, the fault fired
            # before dispatch)
            hb = self._host_view(batch)
            keys = np.asarray(hb.column(self._key_column)).astype(
                np.int64, copy=False)
            self._ingest(hb, keys)
            return
        self._backend.table = table
        for n, a in new_arrays.items():
            self._backend.set_array(n, a)
        self._backend._dropped = dropped
        self._backend.set_dirty_mask(dirty)
        self._late_dev = late
        batch.deliver(viol, last)
        self._admit_token(token)

    def _alloc_stage(self) -> None:
        S = self._stage_slots
        st = {"keys": jnp.zeros(S, jnp.int64),
              "ring": jnp.zeros(S, jnp.int32),
              "count": jnp.zeros((), jnp.int64)}
        for _k, name, _f in self._fold_sig():
            st[name] = jnp.zeros(S, self._backend.get_array(name).dtype)
        self._stage = st

    def _pre_fire_flush(self) -> None:
        """Coalesced batches fold before any fire (watermark/barrier
        semantics are unchanged by buffering), then deferred spill: staged
        host-tier rows must land before any fire merges host parts
        (exactly-once per window). One tiny scalar sync per watermark, a
        buffer transfer only when something was staged. Once nothing is
        in flight for any group, the tiering boundary hook runs: heat
        decay advances and at most one staged warm->hot promotion lands
        (batch-boundary-only residency changes keep the fire path's
        scatter-free invariants and exactly-once intact)."""
        self._coalesce_flush()
        self._drain_spill_stage()
        if self._backend is not None and self._backend.tiering_active:
            if self._backend.tier_boundary():
                # promoted keys arrive with identity window-role planes:
                # the next incremental fire rebuilds them from the panes
                self._inc_dirty = True

    def _drain_spill_stage(self) -> None:
        if self._stage is None:
            return
        # lint: sync-ok spill-stage drain gate, once per fire boundary
        cnt = int(jax.device_get(self._stage["count"]))
        if cnt == 0:
            return
        take = min(cnt, self._stage_slots)
        # transfer only the written prefix, rounded up to a power of two so
        # the slice program compiles O(log S) times, not once per count
        span = min(1 << (take - 1).bit_length() if take > 1 else 1,
                   self._stage_slots)
        host = stall_bounded(
            "transfer.d2h",
            # lint: sync-ok spill-stage drain, one bounded d2h per fire boundary
            lambda: jax.device_get({k: v[:span]
                                    for k, v in self._stage.items()
                                    if k != "count"}),
            scope="device_window")
        DEVICE_STATS.note_d2h(pytree_nbytes(host), take)
        keys = np.asarray(host["keys"])[:take]
        ring = np.asarray(host["ring"])[:take]
        vals = {"__count__": np.ones(take, np.int64)}
        for _k, name, _f in self._fold_sig():
            vals[name] = np.asarray(host[name])[:take]
        self._backend.drain_staged(keys, ring, vals)
        # buffers are reusable (only [0:count) is ever read): reset the
        # write position alone
        self._stage["count"] = jnp.zeros((), jnp.int64)

    def _host_view(self, batch) -> RecordBatch:
        """A host-column view of a batch (CPU fallback: device arrays ARE
        host buffers, so np.asarray is a view, not a transfer)."""
        if isinstance(batch, DeviceRecordBatch):
            # lint: sync-ok CPU-fallback view: np.asarray of a host-backed buffer is zero-copy
            cols = {f.name: np.asarray(batch.device_column(f.name))
                    for f in batch.schema.fields}
            ts = np.asarray(batch.dtimestamps
                            if batch.dtimestamps is not None
                            else batch.timestamps)
            return RecordBatch(batch.schema, cols, ts)
        return batch

    def _fold_native(self, batch: RecordBatch, keys: np.ndarray,
                     panes: np.ndarray) -> None:
        """CPU-fallback fold: native host-index slot resolution + ONE
        donated XLA fold program over all aggregates. The C++ probe beats
        the XLA probe loop ~15x on host cores (see backend.native_slots);
        the scatter folds stay XLA (donated, in-place)."""
        backend = self._backend
        slots = backend.native_slots(keys)
        cap = backend.capacity
        flat = (panes % self._ring).astype(np.int64) * np.int64(cap) \
            + slots.astype(np.int64)
        from ...ops.segment_ops import pow2_ceil

        n = batch.n
        P = pow2_ceil(n)

        def _pad(a: np.ndarray, fill) -> np.ndarray:
            if P == n:
                return a
            return np.concatenate([a, np.full(P - n, fill, a.dtype)])

        sig = self._fold_sig()

        def dispatch():
            vals = tuple(jnp.asarray(_pad(np.asarray(batch.column(f)), 0))
                         for _k, _n, f in sig)
            valid = jnp.asarray(_pad(np.ones(n, bool), False))
            DEVICE_STATS.note_h2d(
                pytree_nbytes(vals) + valid.nbytes + flat.nbytes
                + slots.nbytes, n)
            arrays = {name: backend.get_array(name)
                      for name in self._fire_array_names()}
            prog = _native_fold_program(sig, backend.dirty_block_size)
            return prog(
                arrays, backend.dirty_mask, jnp.asarray(_pad(flat, 0)),
                jnp.asarray(_pad(slots, np.int32(0))), valid, vals)

        try:
            out, dirty, token = self._guard.run(
                dispatch, sites=("transfer.h2d", "device.execute"))
        except DeviceSegmentError as e:
            if e.poison:
                self._dead_letter(self._host_view(batch))
                return  # quarantined before folding; slots claimed but
                # their count plane stays 0 so nothing ever emits
            # the native fold IS already the host-fallback rung: there is
            # no further backend to descend to — disarm injection for
            # this operator and re-run the same fold
            self._degraded = True
            self._guard.active = False
            DEVICE_STATS.note_degraded("device_window")
            out, dirty, token = dispatch()
        for name, a in out.items():
            backend.set_array(name, a)
        backend.set_dirty_mask(dirty)
        self._admit_token(token)

    def _admit_token(self, token) -> None:
        """Bounded in-flight window shared by the device and native ingest
        paths: block on the (k - max_inflight)th step's completion token
        before admitting more work, then drain any landed fires. The wait
        is deadline-bounded: a dispatch that never retires (wedged chip)
        raises StallError into task failover instead of blocking the
        mailbox loop forever — its state futures are unresolvable, so
        restart-from-checkpoint is the only sound rung for this stall."""
        self._inflight.append(token)
        if len(self._inflight) > self._max_inflight:
            tok = self._inflight.popleft()
            if self._guard is not None and self._guard.active:
                WATCHDOG.run("device.execute",
                             lambda: jax.block_until_ready(tok),
                             scope="device_window.inflight")
            else:
                jax.block_until_ready(tok)
            if self._pending:
                self._drain(block=False)

    def _fold(self, batch: RecordBatch, keys: np.ndarray,
              panes: np.ndarray) -> None:
        if self._backend.host_index_active:
            self._fold_native(batch, keys, panes)
            return
        if self._defer:
            # pipelined path: host<->device calls have a large fixed cost
            # (the chip may sit behind a network tunnel), so the whole
            # batch rides ONE upload and nothing syncs back
            self._fold_packed(batch, keys, panes % self._ring)
            return
        ring_idx = panes % self._ring
        slots = self._backend.slots_for_batch(keys)
        valid = slots >= 0
        self._backend.fold_batch("__count__", slots,
                                 np.ones(batch.n, np.int64), valid,
                                 ring_idx=ring_idx)
        for a in self._aggs:
            if a.kind == "count":
                continue
            col = batch.column(a.field)
            name = f"{a.out_name}.sum" if a.kind == "avg" else a.out_name
            self._backend.fold_batch(name, slots, col, valid,
                                     ring_idx=ring_idx)

    def _fold_packed(self, batch: RecordBatch, keys: np.ndarray,
                     ring_idx: np.ndarray) -> None:
        """Pack keys + ring rows + every aggregate column into one [C, B]
        int64 buffer (floats bit-cast via float64), upload once, slice on
        device. Zero host round-trips per batch."""
        rows = [keys, ring_idx]
        col_meta: list[tuple[str, bool]] = []
        for a in self._aggs:
            if a.kind == "count":
                continue
            col = np.asarray(batch.column(a.field))
            name = f"{a.out_name}.sum" if a.kind == "avg" else a.out_name
            if np.issubdtype(col.dtype, np.floating):
                rows.append(np.ascontiguousarray(
                    col.astype(np.float64)).view(np.int64))
                col_meta.append((name, True))
            else:
                rows.append(col.astype(np.int64))
                col_meta.append((name, False))
        packed = np.stack(rows)
        buf = stall_bounded("transfer.h2d",
                            lambda: jnp.asarray(packed),  # the ONE upload
                            scope="device_window")
        DEVICE_STATS.note_h2d(buf.nbytes, batch.n)
        slots = self._backend.slots_for_batch_device(buf[0])
        dring = buf[1]
        valid = slots >= 0
        self._backend.fold_batch("__count__", slots,
                                 jnp.ones(batch.n, jnp.int64), valid,
                                 ring_idx=dring)
        for i, (name, is_float) in enumerate(col_meta):
            vals = buf[2 + i]
            if is_float:
                vals = jax.lax.bitcast_convert_type(vals, jnp.float64)
            self._backend.fold_batch(name, slots, vals, valid,
                                     ring_idx=dring)

    # -- firing (fire loop lives in SliceControlPlane) ----------------------
    # A fire is ONE compiled program (pane merge for every aggregate +
    # emit mask + optional device top-k + health scalars) whose outputs
    # start copying device->host asynchronously at dispatch. In async mode
    # the emission is queued and drained once the copy lands — fires cost
    # no synchronous round-trip, and the watermark is held behind its
    # fires so it never overtakes them downstream.

    def _fire(self, p_end: int) -> None:
        t_fire = time.perf_counter()
        W = self._window_panes
        # never read panes below min_seen: they hold no data and their ring
        # rows may be occupied by live FUTURE panes (row aliasing)
        first = max(p_end - W, self._min_seen_pane)
        if first >= p_end:
            return
        if self._inc_enabled:
            self._fire_incremental(p_end, first, t_fire)
            return
        rows = [(p % self._ring) for p in range(first, p_end)]
        DEVICE_STATS.note_fire_merge_rows(len(rows))
        # constant [W] shape: pad + mask so every fire shares one program
        pane_rows = np.zeros(W, np.int32)
        pane_rows[:len(rows)] = rows
        rows_valid = np.zeros(W, bool)
        rows_valid[:len(rows)] = True
        def dispatch():
            fire_fn = _fire_program(
                tuple((a.kind, a.out_name) for a in self._aggs), self._topk,
                self._aggs[0].value_bits
                if self._topk is not None and self._aggs else 64)
            arrays = {n: self._backend.get_array(n)
                      for n in self._fire_array_names()}
            return fire_fn(self._backend.table, arrays,
                           jnp.asarray(pane_rows), jnp.asarray(rows_valid),
                           self._backend.dropped_device)

        try:
            outs = self._guard.run(dispatch)
        except DeviceSegmentError as e:
            # a fire has no batch to quarantine: persistent failure walks
            # the degradation ladder (state evacuates; the re-dispatch
            # reads the rebuilt backend), or re-raises into task failover
            self._on_segment_failure(e)
            outs = dispatch()
        # the host spill tier's rows merge at materialization; take them
        # NOW (before this fire retires the pane row below)
        host_part = (self._host_fire_part(np.array(rows, np.int32))
                     if self._backend.spill_active else None)
        self._enqueue_fire((p_end, outs, host_part, time.perf_counter()))
        # retire the oldest pane of this window: no future window needs it
        # (skip panes below min_seen — their ring rows belong to live panes)
        if p_end - W >= self._min_seen_pane:
            self._backend.reset_ring_row((p_end - W) % self._ring)
        self._refresh_late(block=True)
        self.stage_s["fire"] += time.perf_counter() - t_fire

    # -- incremental fire engine -------------------------------------------
    def _inc_sigs(self) -> tuple[tuple, tuple]:
        """(invertible, merge-tree) signatures over the fire planes.
        The count plane is always invertible, so ``inv_sig`` is never
        empty; min/max planes go through the merge tree."""
        from ...ops.segment_ops import INVERTIBLE_KINDS

        inv, tree = [("count", "__count__")], []
        for a in self._aggs:
            if a.kind == "count":
                continue
            if a.kind == "avg":
                inv.append(("sum", f"{a.out_name}.sum"))
            elif a.kind in INVERTIBLE_KINDS:
                inv.append((a.kind, a.out_name))
            else:
                tree.append((a.kind, a.out_name))
        return tuple(inv), tuple(tree)

    def _ensure_inc_planes(self, inv_sig: tuple, tree_sig: tuple) -> None:
        """Register the derived window-role planes on the CURRENT backend
        (lazily: the backend is replaced on degrade and rebuilt on
        restore, neither of which carries window-role state)."""
        for kind, name in inv_sig:
            wn = f"{name}.__win__"
            if not self._backend.has_array(wn):
                self._backend.register_array_state(
                    wn, kind, self._backend.get_array(name).dtype,
                    ring=None, role="window")
                self._inc_dirty = True
        for kind, name in tree_sig:
            tn = f"{name}.__tree__"
            if not self._backend.has_array(tn):
                self._backend.register_array_state(
                    tn, kind, self._backend.get_array(name).dtype,
                    ring=2 * self._tree_size, role="window")
                self._inc_dirty = True

    def _fire_incremental(self, p_end: int, first: int,
                          t_fire: float) -> None:
        """O(capacity) fire: seal the newest pane into the running window
        state (or rebuild it from the pane planes when stale), then read
        the merged view — outputs byte-identical to the full-merge path
        for integer aggregates and min/max (float sums may differ in
        rounding order; see docs/PERFORMANCE.md)."""
        W = self._window_panes
        rows = [(p % self._ring) for p in range(first, p_end)]
        inv_sig, tree_sig = self._inc_sigs()
        agg_sig = tuple((a.kind, a.out_name) for a in self._aggs)
        vb = (self._aggs[0].value_bits
              if self._topk is not None and self._aggs else 64)
        L = self._tree_size

        def dispatch():
            self._ensure_inc_planes(inv_sig, tree_sig)
            backend = self._backend
            arrays = {n: backend.get_array(n)
                      for n in self._fire_array_names()}
            sub_row = np.int32((p_end - W) % self._ring)
            sub_valid = np.bool_(p_end - W >= self._min_seen_pane)
            if self._inc_dirty or self._inc_next != p_end:
                pane_rows = np.zeros(self._ring, np.int32)
                rows_valid = np.zeros(self._ring, bool)
                pane_leaves = np.zeros(self._ring, np.int32)
                pane_rows[:len(rows)] = rows
                rows_valid[:len(rows)] = True
                pane_leaves[:len(rows)] = [p % L
                                           for p in range(first, p_end)]
                rb = _rebuild_program(inv_sig, tree_sig, L)
                view, new_wins, new_trees = rb(
                    arrays, jnp.asarray(pane_rows), jnp.asarray(rows_valid),
                    jnp.asarray(pane_leaves), sub_row, sub_valid)
                rows_read = sealed = len(rows)
            else:
                seal = _seal_program(inv_sig, tree_sig)
                wins = {n: backend.get_array(f"{n}.__win__")
                        for _k, n in inv_sig}
                trees = {n: backend.get_array(f"{n}.__tree__")
                        for _k, n in tree_sig}
                view, new_wins, new_trees = seal(
                    arrays, wins, trees,
                    np.int32((p_end - 1) % self._ring), sub_row, sub_valid,
                    np.int32((p_end - 1) % L),
                    np.int32((p_end - 1 - W) % L))
                rows_read = 2 if bool(sub_valid) else 1
                sealed = 1
            fire_fn = _fire_inc_program(agg_sig, self._topk, vb)
            outs = fire_fn(backend.table, view, backend.dropped_device)
            return outs, new_wins, new_trees, rows_read, sealed

        try:
            outs, new_wins, new_trees, rows_read, sealed = \
                self._guard.run(dispatch)
        except DeviceSegmentError as e:
            # persistent failure may degrade (state evacuates to a fresh
            # backend) — and the seal DONATED the window-role buffers, so
            # the retry must never re-seal: force the rebuild branch,
            # which reads only the (restored) pane planes
            self._on_segment_failure(e)
            self._inc_dirty = True
            outs, new_wins, new_trees, rows_read, sealed = dispatch()
        for _k, n in inv_sig:
            self._backend.set_array(f"{n}.__win__", new_wins[n])
        for _k, n in tree_sig:
            self._backend.set_array(f"{n}.__tree__", new_trees[n])
        DEVICE_STATS.note_panes_sealed(sealed)
        DEVICE_STATS.note_fire_merge_rows(rows_read)
        self._inc_dirty = False
        self._inc_next = p_end + 1
        # host spill tier merges at materialization; take it BEFORE the
        # retire below (same ordering as the full-merge path)
        host_part = (self._host_fire_part(np.array(rows, np.int32))
                     if self._backend.spill_active else None)
        self._enqueue_fire((p_end, outs, host_part, time.perf_counter()))
        if p_end - W >= self._min_seen_pane:
            self._backend.reset_ring_row((p_end - W) % self._ring)
        self._refresh_late(block=True)
        self.stage_s["fire"] += time.perf_counter() - t_fire

    def _fire_array_names(self) -> list[str]:
        names = ["__count__"]
        for a in self._aggs:
            if a.kind == "count":
                continue
            names.append(f"{a.out_name}.sum" if a.kind == "avg"
                         else a.out_name)
        return names

    def _host_fire_part(self, pane_rows: np.ndarray):
        """Window results for spilled keys (numpy merges over the host
        tier's ring rows)."""
        ht = self._backend.host_tier
        hcount = ht.fire("__count__", pane_rows)
        mask = hcount > 0
        if not mask.any():
            return None
        keys = ht.keys()[mask]
        res: dict[str, np.ndarray] = {}
        for a in self._aggs:
            if a.kind == "count":
                res[a.out_name] = hcount[mask]
            elif a.kind == "avg":
                s = ht.fire(f"{a.out_name}.sum", pane_rows)[mask]
                res[a.out_name] = s / np.maximum(hcount[mask],
                                                 1).astype(s.dtype)
            else:
                res[a.out_name] = ht.fire(a.out_name, pane_rows)[mask]
        return keys, res

    def _materialize(self, item) -> None:
        t_drain = time.perf_counter()
        p_end, outs, host_part, t0 = item
        if self._guard is None or self._guard.active:
            # ONE deadline-bounded transfer for everything (device_get is
            # idempotent: a stall-abandoned read re-runs safely)
            host = stall_bounded("transfer.d2h",
                                 # lint: sync-ok fire materialization: the one amortized d2h per pane fire
                                 lambda: jax.device_get(outs),
                                 scope="device_window")
        else:
            # lint: sync-ok degraded-mode fire materialization (host buffers, a view)
            host = jax.device_get(outs)   # degraded: host buffers, a view
        d2h_bytes = pytree_nbytes(host)
        if self._topk is not None:
            keys_k, ok, results, dropped, occ = host
            self._backend.apply_health(dropped, occ)
            sel = np.asarray(ok)
            keys = np.asarray(keys_k)[sel]
            results = {n: np.asarray(v)[sel] for n, v in results.items()}
        else:
            table, emit, results, dropped, occ = host
            self._backend.apply_health(dropped, occ)
            mask = np.asarray(emit)
            idx = np.flatnonzero(mask)
            keys = np.asarray(table)[idx]
            results = {n: np.asarray(v)[idx] for n, v in results.items()}
        if host_part is not None:
            hkeys, hres = host_part
            keys = np.concatenate([keys, hkeys])
            results = {n: np.concatenate(
                [v, hres[n].astype(v.dtype, copy=False)])
                for n, v in results.items()}
            if self._topk is not None and len(keys) > self._topk:
                order = np.argsort(
                    -results[self._aggs[0].out_name],
                    kind="stable")[:self._topk]
                keys = keys[order]
                results = {n: v[order] for n, v in results.items()}
        if self._topk is None and len(keys) > 1:
            # canonical emission order: raw slot order leaks table-insert
            # history, so a restored (or degraded, or tiered) run would
            # emit the same rows in a different order than the run it
            # replaces; host-side sort on the drain stage, off the device
            # path (top-k already emits in rank order)
            order = np.argsort(keys, kind="stable")
            keys = keys[order]
            results = {n: v[order] for n, v in results.items()}
        DEVICE_STATS.note_d2h(d2h_bytes, len(keys))
        if len(keys):
            self._emit_rows(p_end, keys, results)
        self._note_latency(t0)
        self.stage_s["drain"] += time.perf_counter() - t_drain

    def _emit_rows(self, p_end: int, keys: np.ndarray,
                   results: dict[str, np.ndarray]) -> None:
        if self._validate_batches and len(keys):
            # screen fire RESULTS too: a non-finite aggregate (however it
            # got into the plane) rides the dead-letter output, not the
            # main stream
            bad = np.zeros(len(keys), bool)
            for v in results.values():
                if np.issubdtype(np.asarray(v).dtype, np.floating):
                    bad |= ~np.isfinite(v)
            if bad.any():
                DEVICE_STATS.note_dead_letter(int(bad.sum()))
                keep = ~bad
                keys = keys[keep]
                results = {n_: v[keep] for n_, v in results.items()}
                if not len(keys):
                    return
        n = len(keys)
        start = (p_end - self._window_panes) * self._pane + self._offset
        end = p_end * self._pane + self._offset
        cols: dict[str, np.ndarray] = {self._key_column: keys}
        fields: list[tuple[str, Any]] = [(self._key_column, np.int64)]
        if self._emit_bounds:
            cols["window_start"] = np.full(n, start, np.int64)
            cols["window_end"] = np.full(n, end, np.int64)
            fields += [("window_start", np.int64), ("window_end", np.int64)]
        # emit in AggSpec declaration order — the fire program's results
        # ride a jax pytree, which canonicalizes dict keys to SORTED
        # order, so iterating `results` directly would emit columns
        # alphabetically instead of as the user declared them
        for a in self._aggs:
            vals = results[a.out_name]
            cols[a.out_name] = vals
            fields.append((a.out_name, vals.dtype.type))
        schema = Schema(fields)
        ts = np.full(n, end - 1, np.int64)
        self.output.emit(RecordBatch(schema, cols, ts))

    def finish(self) -> None:
        self._coalesce_flush()
        self._drain(block=True)
        self._refresh_late(block=True)
        if self._backend is not None and self._backend.tiering_active:
            self._backend.prefetch_pipeline.close()

    def _refresh_late(self, block: bool = False) -> None:
        """Sync the host cache of the device late-drop counter. Non-
        blocking by default (only reads a counter whose value has already
        landed); fire and checkpoint boundaries pass block=True. Metrics
        scrapes read the cache alone and can never stall the hot loop."""
        if self._late_dev is None:
            return
        ready = getattr(self._late_dev, "is_ready", None)
        if block or ready is None or ready():
            # lint: sync-ok boundary-amortized refresh; scrapes read the cache (ISSUE 8)
            self._late_cached = int(jax.device_get(self._late_dev))

    @property
    def late_dropped(self) -> int:
        # cached device counter: a /metrics scrape must not force a
        # device sync mid-pipeline (satellite of ISSUE 8); the cache is
        # refreshed at fire and checkpoint boundaries
        return self._late_dropped + self._late_cached

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        self._drain(block=True)
        self._pre_fire_flush()  # staged spill rows belong in the snapshot
        self._refresh_late(block=True)
        return {"keyed": {"backend": self._backend.snapshot(checkpoint_id),
                          "meta": self._control_meta()}}
