"""Sink operator: terminal operator wrapping a SinkWriter.

Analog of the reference's SinkWriterOperator (sink2 runtime). Flushes on
checkpoint (two-phase pre-commit) and snapshots writer state.
"""

from __future__ import annotations

from typing import Optional

from ...connectors.core import Sink, SinkWriter
from ...core.functions import SinkFunction
from ...core.records import RecordBatch
from .base import OneInputOperator, OperatorContext, Output

__all__ = ["SinkOperator", "FunctionSinkOperator"]


class SinkOperator(OneInputOperator):
    def __init__(self, sink: Sink, name: str = "Sink"):
        super().__init__(name)
        self._sink = sink
        self._writer: Optional[SinkWriter] = None

    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        self._writer = self._sink.create_writer(ctx.subtask_index)

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        if operator_snapshot is not None:
            self._writer.restore(operator_snapshot)

    def process_batch(self, batch: RecordBatch) -> None:
        from ..faults import fire_with_retries
        fire_with_retries("sink.invoke")
        self._writer.write_batch(batch)

    def snapshot_state(self, checkpoint_id: int) -> dict:
        self._writer.flush()
        self._writer.prepare_commit(checkpoint_id)
        return {"operator": self._writer.snapshot()}

    def notify_checkpoint_complete(self, checkpoint_id: int,
                                   is_savepoint: bool = False) -> None:
        self._writer.commit(checkpoint_id)

    def finish(self) -> None:
        self._writer.flush()
        # end of input: stage and commit everything outstanding (reference
        # StreamingFileSink closes in-progress files on final checkpoint)
        self._writer.prepare_commit(1 << 62)
        self._writer.commit(1 << 62)

    def close(self) -> None:
        self._writer.close()


class FunctionSinkOperator(OneInputOperator):
    """Wraps a plain SinkFunction (reference StreamSink)."""

    def __init__(self, fn: SinkFunction, name: str = "Sink"):
        super().__init__(name)
        self._fn = fn

    def process_batch(self, batch: RecordBatch) -> None:
        from ..faults import fire_with_retries
        fire_with_retries("sink.invoke")
        if self._fn.invoke_batch(batch):
            return
        for i, row in enumerate(batch.iter_rows()):
            ts = int(batch.timestamps[i])
            self._fn.invoke(row, None if ts == -(1 << 62) else ts)

    def close(self) -> None:
        self._fn.close()
