"""Connected broadcast stream operator: keyed input + replicated state.

Analog of the reference's CoBroadcastWithKeyedOperator
(flink-streaming-java .../api/operators/co/CoBroadcastWithKeyedOperator
.java:64) behind BroadcastConnectedStream.process
(.../api/datastream/BroadcastConnectedStream.java:55): input 1 is the
keyed event stream, input 2 is broadcast — every subtask receives every
broadcast record and applies it to its own replica of the broadcast
(map) state, so replicas stay identical as long as the user function's
broadcast-side updates are deterministic (the same contract the
reference documents).

Checkpointing: the broadcast maps ride the OPERATOR (non-keyed) snapshot
under ``"broadcast"``. Every subtask snapshots an identical copy; on a
same-parallelism restore each subtask takes its own copy back, and on
rescale OperatorStateBackend.redistribute hands every new subtask the
first copy (identical by construction) — the reference redistributes
broadcast state the same way. The keyed side uses the ordinary keyed
backend + timers of KeyedProcessOperator.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.functions import (
    Collector, KeyedBroadcastProcessFunction, _ReadOnlyMap, copy_per_subtask,
)
from ...core.records import MIN_TIMESTAMP, RecordBatch, Schema
from ...runtime.timers import InternalTimerService
from .base import OperatorContext, Output, TwoInputOperator

__all__ = ["CoBroadcastWithKeyedOperator"]


class CoBroadcastWithKeyedOperator(TwoInputOperator):
    def __init__(self, fn: KeyedBroadcastProcessFunction, key_extractor,
                 descriptors, out_schema: Optional[Schema] = None,
                 name: str = "CoBroadcastWithKeyed"):
        super().__init__(name)
        self._fn = copy_per_subtask(fn)
        self._key_extractor = key_extractor
        self._descriptors = list(descriptors)
        self._maps: dict[str, dict] = {d.name: {} for d in self._descriptors}
        self._out_schema = out_schema
        self._backend = None
        self._timers: Optional[InternalTimerService] = None
        self._pending_rows: list = []
        self._pending_ts: list[int] = []

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        self._backend = ctx.create_keyed_backend()
        self._timers = InternalTimerService(
            ctx.key_group_range, ctx.max_parallelism,
            on_event_time=self._fire_timer, on_processing_time=None)

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        if keyed_snapshots:
            self._backend.restore([s["backend"] for s in keyed_snapshots])
            self._timers.restore([s["timers"] for s in keyed_snapshots])
        if operator_snapshot:
            restored = operator_snapshot.get("broadcast") or {}
            for name, m in restored.items():
                self._maps[name] = dict(m)

    def open(self) -> None:
        from .simple import _runtime_context

        self._fn.open(_runtime_context(self, self._backend))

    def close(self) -> None:
        self._fn.close()

    # -- broadcast state access -------------------------------------------
    def _view(self, name: str) -> _ReadOnlyMap:
        return _ReadOnlyMap(self._maps[name])

    def _rw(self, name: str) -> dict:
        return self._maps[name]

    # -- output ------------------------------------------------------------
    def _collector(self) -> Collector:
        def sink(value, timestamp):
            self._pending_rows.append(value)
            self._pending_ts.append(
                MIN_TIMESTAMP if timestamp is None else int(timestamp))
        return Collector(sink)

    def _flush_pending(self) -> None:
        if not self._pending_rows:
            return
        out, self._out_schema = RecordBatch.from_rows_infer(
            self._out_schema, self._pending_rows, self._pending_ts)
        self.output.emit(out)
        self._pending_rows, self._pending_ts = [], []

    # -- data path ---------------------------------------------------------
    def process_batch1(self, batch: RecordBatch) -> None:
        keys = self._key_extractor(batch)
        out = self._collector()
        for i in range(batch.n):
            key = keys[i]
            key = key.item() if isinstance(key, np.generic) else key
            self._backend.set_current_key(key)
            ts = int(batch.timestamps[i])
            ctx = KeyedBroadcastProcessFunction.ReadOnlyContext(
                None if ts == MIN_TIMESTAMP else ts, key, self._view,
                timer_service=self._timer_api(key))
            self._fn.process_element(batch.row(i), ctx, out)
        self._flush_pending()

    def process_batch2(self, batch: RecordBatch) -> None:
        out = self._collector()
        for i in range(batch.n):
            ts = int(batch.timestamps[i])
            ctx = KeyedBroadcastProcessFunction.Context(
                None if ts == MIN_TIMESTAMP else ts, self._rw,
                apply_keyed=self._apply_to_keyed_state)
            self._fn.process_broadcast_element(batch.row(i), ctx, out)
        self._flush_pending()

    def _apply_to_keyed_state(self, descriptor, fn) -> None:
        for key in list(self._backend.keys(descriptor.name)):
            self._backend.set_current_key(key)
            fn(key, self._backend.get_partitioned_state(descriptor))

    # -- timers ------------------------------------------------------------
    def _timer_api(self, key):
        op = self

        class _TimerApi:
            current_watermark = property(
                lambda s: op._timers.current_watermark)

            def register_event_time_timer(self, ts, namespace=None):
                op._timers.register_event_time_timer(key, ts, namespace)

            def delete_event_time_timer(self, ts, namespace=None):
                op._timers.delete_event_time_timer(key, ts, namespace)

        return _TimerApi()

    def _fire_timer(self, key, ts, namespace) -> None:
        self._backend.set_current_key(key)
        out = self._collector()
        ctx = KeyedBroadcastProcessFunction.ReadOnlyContext(
            ts, key, self._view, timer_service=self._timer_api(key))
        self._fn.on_timer(ts, ctx, out)
        self._flush_pending()

    def process_watermark_n(self, input_index: int, watermark) -> None:
        # fire timers and flush their output BEFORE the base class
        # forwards the watermark (KeyedProcessOperator's ordering): rows
        # produced by on_timer carry ts <= wm and would otherwise arrive
        # behind the watermark that triggered them — late by construction
        wms = list(self._input_watermarks)
        wms[input_index] = watermark.timestamp
        self._timers.advance_watermark(min(wms))
        self._flush_pending()
        super().process_watermark_n(input_index, watermark)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {
            "keyed": {"backend": self._backend.snapshot(checkpoint_id),
                      "timers": self._timers.snapshot()},
            "operator": {"broadcast": {n: dict(m)
                                       for n, m in self._maps.items()}},
        }
