"""Stream operator base: the unit of computation inside a task.

Analog of flink-streaming-java's operator layer
(api/operators/AbstractStreamOperator.java:93, StreamOperator, Output,
OperatorChain.java:108). Operators are batch-oriented: ``process_batch``
receives a whole RecordBatch; control elements (watermarks, barriers, latency
markers) arrive through dedicated methods in channel order. Chained operators
are fused by direct method calls (the ChainingOutput analog) — and when every
operator in a chain exposes a jax-traceable batch function the whole chain
compiles into ONE XLA program (see runtime/compiled.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

import numpy as np

from ...core.config import Configuration, PipelineOptions, StateOptions
from ...core.elements import LatencyMarker, Watermark
from ...core.keygroups import KeyGroupRange, key_group_range_for_operator
from ...core.records import RecordBatch, Schema
from ...metrics.profiler import DEVICE_LEDGER, set_dispatch_context
from ...state.backend import KeyedStateBackend, OperatorStateBackend, \
    create_backend
from ..timers import InternalTimerService

__all__ = ["OperatorContext", "Output", "CollectingOutput", "StreamOperator",
           "OneInputOperator", "TwoInputOperator", "OperatorChain"]


@dataclass
class OperatorContext:
    """Everything an operator needs from its task (reference
    StreamingRuntimeContext + StreamConfig)."""

    task_name: str
    subtask_index: int
    parallelism: int
    max_parallelism: int
    config: Configuration = field(default_factory=Configuration)
    metrics: Any = None
    processing_time: Callable[[], int] = lambda: int(time.time() * 1000)
    operator_id: str = ""
    kv_registry: Any = None  # queryable-state registry (local job scope)

    @property
    def key_group_range(self) -> KeyGroupRange:
        return key_group_range_for_operator(
            self.max_parallelism, self.parallelism, self.subtask_index)

    def create_keyed_backend(self, name: str = None,
                             **kwargs) -> KeyedStateBackend:
        """``name`` overrides the configured backend — operators whose
        state shapes a partial backend cannot hold (e.g. the host
        WindowOperator's per-window aggregating state on the tpu value
        plane) pin the backend that can."""
        if name is None:
            name = self.config.get(StateOptions.BACKEND)
        backend = create_backend(name, self.key_group_range,
                                 self.max_parallelism, config=self.config,
                                 **kwargs)
        backend.kv_registry = self.kv_registry
        return backend


class Output:
    """Downstream edge of an operator (reference Output<StreamRecord>)."""

    def emit(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def emit_watermark(self, watermark: Watermark) -> None:
        raise NotImplementedError

    def emit_latency_marker(self, marker: LatencyMarker) -> None:
        pass

    def emit_side(self, tag: str, batch: RecordBatch) -> None:
        raise NotImplementedError(f"no side output wired for tag {tag!r}")


class CollectingOutput(Output):
    """Buffers everything — tail of test harnesses and of compiled segments."""

    def __init__(self):
        self.batches: list[RecordBatch] = []
        self.watermarks: list[Watermark] = []
        self.latency_markers: list[LatencyMarker] = []
        self.side: dict[str, list[RecordBatch]] = {}

    def emit(self, batch: RecordBatch) -> None:
        if batch.n:
            self.batches.append(batch)

    def emit_watermark(self, watermark: Watermark) -> None:
        self.watermarks.append(watermark)

    def emit_latency_marker(self, marker: LatencyMarker) -> None:
        self.latency_markers.append(marker)

    def emit_side(self, tag: str, batch: RecordBatch) -> None:
        self.side.setdefault(tag, []).append(batch)

    def rows(self) -> list:
        return [r for b in self.batches for r in b.iter_rows()]

    def clear(self) -> None:
        self.batches.clear()
        self.watermarks.clear()
        self.side.clear()


class StreamOperator:
    """Lifecycle mirrors AbstractStreamOperator: setup -> initialize_state ->
    open -> (process loop) -> finish -> close."""

    def __init__(self, name: str = ""):
        self.name = name or type(self).__name__
        self.ctx: Optional[OperatorContext] = None
        self.output: Output = None  # type: ignore[assignment]
        self.current_watermark: int = -(1 << 62)
        self._latency_hist = None
        self.latency_markers_seen = 0
        self._ledger_job = ""
        self._ledger_ident = self.name

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        self.ctx = ctx
        self.output = output
        # device-time ledger attribution identity: the owning job's name
        # plus the chain-stable operator key (see OperatorChain)
        self._ledger_job = str(ctx.config.get(PipelineOptions.NAME))
        self._ledger_ident = getattr(self, "_op_key", self.name)
        metrics = getattr(ctx, "metrics", None)
        if metrics is not None and hasattr(metrics, "operator_group"):
            # per-operator scope (reference AbstractStreamOperator's
            # WatermarkGauge + latency histogram under the operator group)
            g = metrics.operator_group(getattr(self, "_op_key", self.name))
            g.gauge("currentInputWatermark", lambda: self.current_watermark)
            g.gauge("watermarkLag", self._watermark_lag_ms)
            self._latency_hist = g.histogram("latency")

    def _watermark_lag_ms(self):
        """Wall-clock lag behind the operator's event-time watermark; NaN
        until the first real watermark (MIN would read as astronomic)."""
        if self.current_watermark <= -(1 << 61):
            return float("nan")
        return max(0, int(time.time() * 1000) - self.current_watermark)

    def _enter_dispatch(self) -> None:
        """Pin this operator as the (job, operator) owner of device-time
        ledger samples recorded on the current thread — called at every
        batch/watermark entry into the operator. One attribute read when
        the ledger is disabled."""
        if DEVICE_LEDGER.enabled:
            set_dispatch_context(self._ledger_job, self._ledger_ident)

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        pass

    def open(self) -> None:
        pass

    def finish(self) -> None:
        """End of input: flush buffers (reference StreamOperator.finish)."""

    def close(self) -> None:
        pass

    # -- data path ---------------------------------------------------------
    def process_watermark(self, watermark: Watermark) -> None:
        self.current_watermark = watermark.timestamp
        self.output.emit_watermark(watermark)

    def process_latency_marker(self, marker: LatencyMarker) -> None:
        # record source->here latency at EVERY hop, then forward (the
        # reference records into the operator's latency histogram keyed
        # by source; one histogram per operator suffices here)
        self.latency_markers_seen += 1
        if self._latency_hist is not None:
            self._latency_hist.update(
                (time.time() - marker.marked_time) * 1e3)
        self.output.emit_latency_marker(marker)

    def advance_processing_time(self, now_ms: int) -> None:
        """Driven by the task's step loop for processing-time timers."""

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        """Return {'keyed': <per-kg snapshot>|None, 'operator': dict|None,
        'timers': dict|None} — serializable."""
        return {}

    def notify_checkpoint_complete(self, checkpoint_id: int,
                                   is_savepoint: bool = False) -> None:
        # operators owning a keyed backend (convention: self._backend)
        # forward completions so backends with deferred artifact cleanup
        # (changelog generations) can prune on SUBSUMPTION, not snapshots
        backend = getattr(self, "_backend", None)
        if backend is not None and hasattr(backend,
                                           "notify_checkpoint_complete"):
            backend.notify_checkpoint_complete(checkpoint_id,
                                               is_savepoint=is_savepoint)

    def notify_checkpoint_aborted(self, checkpoint_id: int) -> None:
        backend = getattr(self, "_backend", None)
        if backend is not None and hasattr(backend,
                                           "notify_checkpoint_aborted"):
            backend.notify_checkpoint_aborted(checkpoint_id)


class OneInputOperator(StreamOperator):
    def process_batch(self, batch: RecordBatch) -> None:
        raise NotImplementedError


class TwoInputOperator(StreamOperator):
    """Two-input operator (reference TwoInputStreamOperator): watermark is the
    min across inputs (handled by the task's valve per input, then min here)."""

    def __init__(self, name: str = ""):
        super().__init__(name)
        self._input_watermarks = [-(1 << 62), -(1 << 62)]

    def process_batch1(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def process_batch2(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def process_watermark_n(self, input_index: int, watermark: Watermark) -> None:
        self._input_watermarks[input_index] = watermark.timestamp
        combined = min(self._input_watermarks)
        if combined > self.current_watermark:
            self.process_watermark(Watermark(combined))


class _ChainingOutput(Output):
    """Direct-call edge between chained operators (reference ChainingOutput)."""

    def __init__(self, downstream: OneInputOperator,
                 side_router: Optional[dict[str, Output]] = None):
        self._op = downstream
        self._side = side_router or {}

    def emit(self, batch: RecordBatch) -> None:
        if batch.n:
            self._op._enter_dispatch()
            self._op.process_batch(batch)

    def emit_watermark(self, watermark: Watermark) -> None:
        self._op._enter_dispatch()
        self._op.process_watermark(watermark)

    def emit_latency_marker(self, marker: LatencyMarker) -> None:
        self._op.process_latency_marker(marker)

    def emit_side(self, tag: str, batch: RecordBatch) -> None:
        out = self._side.get(tag)
        if out is not None:
            out.emit(batch)


class OperatorChain:
    """A fused sequence of operators executed by one task
    (reference OperatorChain.java:108). Head receives task input; tail writes
    the task's record writer."""

    def __init__(self, operators: list[StreamOperator], ctx: OperatorContext,
                 tail_output: Output,
                 side_outputs: Optional[dict[str, Output]] = None):
        self.operators = operators
        self.ctx = ctx
        for i, op in enumerate(operators):
            # stable per-operator id for state snapshots (unique in the chain)
            op._op_key = f"{i}:{op.name}"
        # wire back-to-front
        next_output = tail_output
        for op in reversed(operators):
            op.setup(ctx, next_output)
            next_output = _ChainingOutput(op, side_outputs)
        self.head: StreamOperator = operators[0]

    @property
    def head_one_input(self) -> OneInputOperator:
        return self.head  # type: ignore[return-value]

    def initialize_state(self, per_operator_snapshots: Optional[dict]) -> None:
        for op in self.operators:
            snaps = (per_operator_snapshots or {}).get(_op_key(op), None)
            op.initialize_state(
                snaps.get("keyed_list", []) if snaps else [],
                snaps.get("operator") if snaps else None)

    def open(self) -> None:
        for op in reversed(self.operators):  # downstream first, like reference
            op.open()

    def process_batch(self, batch: RecordBatch) -> None:
        self.head._enter_dispatch()
        self.head_one_input.process_batch(batch)

    def process_batch_n(self, input_index: int, batch: RecordBatch) -> None:
        """Route a batch to input 0/1 of a two-input head."""
        head: TwoInputOperator = self.head  # type: ignore[assignment]
        head._enter_dispatch()
        if input_index == 0:
            head.process_batch1(batch)
        else:
            head.process_batch2(batch)

    def process_watermark(self, watermark: Watermark) -> None:
        self.head._enter_dispatch()
        self.head.process_watermark(watermark)

    def process_watermark_n(self, input_index: int,
                            watermark: Watermark) -> None:
        self.head._enter_dispatch()
        if isinstance(self.head, TwoInputOperator):
            self.head.process_watermark_n(input_index, watermark)
        else:
            self.head.process_watermark(watermark)

    def process_latency_marker(self, marker: LatencyMarker) -> None:
        """Route a latency probe through every chained operator (each
        records its source->operator latency) out to the tail writers."""
        self.head.process_latency_marker(marker)

    def advance_processing_time(self, now_ms: int) -> None:
        for op in self.operators:
            op.advance_processing_time(now_ms)

    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {_op_key(op): op.snapshot_state(checkpoint_id)
                for op in self.operators}

    def notify_checkpoint_complete(self, checkpoint_id: int,
                                   is_savepoint: bool = False) -> None:
        for op in self.operators:
            op.notify_checkpoint_complete(checkpoint_id,
                                          is_savepoint=is_savepoint)

    def notify_checkpoint_aborted(self, checkpoint_id: int) -> None:
        for op in self.operators:
            op.notify_checkpoint_aborted(checkpoint_id)

    def finish(self) -> None:
        for op in self.operators:
            op.finish()

    def close(self) -> None:
        for op in self.operators:
            op.close()


def _op_key(op: StreamOperator) -> str:
    return getattr(op, "_op_key", op.name)
