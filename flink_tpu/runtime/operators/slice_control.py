"""Shared host control plane for slice-window device operators.

Both the single-chip DeviceWindowAggOperator and the mesh
MeshWindowAggOperator run the same scalar protocol around their compiled
steps: pane arithmetic, late-record filtering, the watermark-driven fire
loop, and the fired/seen-pane metadata that rides along with keyed
snapshots. This mixin holds that protocol once (the analog of the logic in
the reference's WindowOperator.processElement:278 / onEventTime:437 that
is independent of the state backend), so a fix to the boundary math lands
in every device operator.

Subclasses provide:
  _fold(batch, keys, panes)   — accumulate one filtered batch
  _fire(p_end)                — merge + emit the window ending at pane
                                boundary p_end, then retire its oldest row
  _pre_fire_flush()           — drain any staged input (mesh buffering);
                                default no-op
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

from ...core.elements import Watermark
from ...core.records import RecordBatch

__all__ = ["SliceControlPlane", "AsyncFireQueue", "CoalescingIngest"]

_MAX_FIRE_SAMPLES = 65536


class CoalescingIngest:
    """Coalesced ingest dispatch: consecutive same-schema micro-batches
    accumulate host-side up to a configurable record target, so ONE
    compiled step dispatch amortizes its fixed cost (tunnel RTT, program
    launch, pane bookkeeping) over several upstream batches. The buffer
    flushes when the record target is reached, when an incompatible batch
    arrives, when a configured age deadline has passed (checked at the
    next admit — no timer thread), and unconditionally before fires,
    snapshots and finish (watermark/barrier semantics are unchanged: a
    record admitted before a watermark is folded before that watermark's
    fires). Subclasses implement ``_process_batch_now(batch)``."""

    def _init_coalescer(self) -> None:
        self._coalesce_target = 0     # records; <= 1 disables
        self._coalesce_timeout_s = 0.0
        self._co_buf: list = []
        self._co_records = 0
        self._co_deadline: Optional[float] = None

    @staticmethod
    def _co_signature(batch) -> tuple:
        return (type(batch).__name__,
                tuple((f.name, np.dtype(f.dtype).str if f.dtype is not object
                       else "object") for f in batch.schema.fields))

    def _coalesce_admit(self, batch) -> None:
        if self._co_buf and \
                self._co_signature(self._co_buf[0]) != \
                self._co_signature(batch):
            self._coalesce_flush()
        self._co_buf.append(batch)
        self._co_records += batch.n
        now = time.monotonic()
        if self._co_deadline is None and self._coalesce_timeout_s > 0:
            self._co_deadline = now + self._coalesce_timeout_s
        if self._co_records >= self._coalesce_target or (
                self._co_deadline is not None and now >= self._co_deadline):
            self._coalesce_flush()

    def _coalesce_flush(self) -> None:
        buf, self._co_buf = self._co_buf, []
        self._co_records = 0
        self._co_deadline = None
        if not buf:
            return
        if len(buf) == 1:
            self._process_batch_now(buf[0])
            return
        from ...metrics.device import DEVICE_STATS
        DEVICE_STATS.note_batches_coalesced(len(buf))
        self._process_batch_now(self._co_merge(buf))

    @staticmethod
    def _co_merge(buf: list):
        from ...core.device_records import DeviceRecordBatch

        first = buf[0]
        if isinstance(first, DeviceRecordBatch):
            import jax.numpy as jnp

            cols = {f.name: jnp.concatenate(
                        [b.device_column(f.name) for b in buf])
                    for f in first.schema.fields}
            dts = (jnp.concatenate([b.dtimestamps for b in buf])
                   if first.dtimestamps is not None else None)
            return DeviceRecordBatch(
                first.schema, cols, dts,
                min(b.ts_min for b in buf), max(b.ts_max for b in buf),
                ts_column=first.ts_column)
        cols = {f.name: np.concatenate([b.column(f.name) for b in buf])
                for f in first.schema.fields}
        ts = np.concatenate([b.timestamps for b in buf])
        return RecordBatch(first.schema, cols, ts)

    def _process_batch_now(self, batch) -> None:
        raise NotImplementedError


class AsyncFireQueue:
    """Asynchronous fire emission, shared by the single-chip and mesh
    device operators: a fire's compiled outputs start copying device->host
    at dispatch (copy_to_host_async); emission is queued and drained once
    the copy lands, and watermarks are held behind their fires so they
    never overtake results downstream. The hot loop never blocks on a
    fire. Subclasses implement ``_materialize(item)``; an item is a tuple
    whose second element is the fire's device-output pytree."""

    _async: bool

    def _init_async_fires(self) -> None:
        self._pending: deque = deque()

    def _enqueue_fire(self, item: tuple) -> None:
        import jax

        for leaf in jax.tree_util.tree_leaves(item[1]):
            leaf.copy_to_host_async()
        if self._async:
            self._pending.append(item)
        else:
            self._materialize(item)

    def _drain(self, block: bool = False) -> None:
        import jax

        while self._pending:
            head = self._pending[0]
            if isinstance(head, Watermark):
                self.output.emit_watermark(head)
                self._pending.popleft()
                continue
            if not block and not all(
                    leaf.is_ready()
                    for leaf in jax.tree_util.tree_leaves(head[1])):
                return
            self._pending.popleft()
            self._materialize(head)

    def _emit_watermark_out(self, watermark: Watermark) -> None:
        if self._async and self._pending:
            self._pending.append(watermark)
        else:
            self.output.emit_watermark(watermark)

    def _note_latency(self, t0: float) -> None:
        if self._async and len(self.fire_latencies_ms) < _MAX_FIRE_SAMPLES:
            self.fire_latencies_ms.append((time.perf_counter() - t0) * 1e3)

    def _materialize(self, item: tuple) -> None:
        raise NotImplementedError


class SliceControlPlane:
    # set by subclass __init__
    _pane: int
    _offset: int
    _window_panes: int
    _ring: int

    def _init_control_plane(self) -> None:
        # windows ending at pane boundary p_end for all p_end <
        # _fired_boundary have fired; panes < _fired_boundary - W are
        # retired (ring rows reusable, records late)
        self._fired_boundary: Optional[int] = None
        self._min_seen_pane: Optional[int] = None
        self._max_seen_pane: Optional[int] = None
        self._late_dropped = 0
        # wall-clock of each window fire (merge + emit), for the p99
        # window-fire latency metric (BASELINE.md); bounded reservoir.
        # Async-firing operators set _record_fire_latency False and record
        # dispatch->drain themselves.
        self.fire_latencies_ms: list[float] = []
        self._record_fire_latency = True

    # -- metadata ----------------------------------------------------------
    def _control_meta(self) -> dict:
        return {"fired_boundary": self._fired_boundary,
                "min_seen_pane": self._min_seen_pane,
                "max_seen_pane": self._max_seen_pane,
                "watermark": self.current_watermark}

    def _restore_control_meta(self, metas: list[dict]) -> None:
        fires = [m["fired_boundary"] for m in metas
                 if m.get("fired_boundary") is not None]
        seens = [m["max_seen_pane"] for m in metas
                 if m.get("max_seen_pane") is not None]
        mins = [m["min_seen_pane"] for m in metas
                if m.get("min_seen_pane") is not None]
        self._fired_boundary = min(fires) if fires else None
        self._max_seen_pane = max(seens) if seens else None
        self._min_seen_pane = min(mins) if mins else None
        self.current_watermark = max(m["watermark"] for m in metas)

    # -- data path ---------------------------------------------------------
    def _ingest(self, batch: RecordBatch, keys: np.ndarray) -> None:
        """Late-filter + pane-span bookkeeping, then hand the surviving
        records to the subclass's _fold."""
        panes = ((batch.timestamps - self._offset) // self._pane).astype(
            np.int64)
        if self._fired_boundary is not None:
            # late = every window containing the pane has fired (its ring
            # row may already be retired/reused)
            first_open = self._fired_boundary - self._window_panes
            late = panes < first_open
            n_late = int(late.sum())
            if n_late:
                self._late_dropped += n_late
                keep = ~late
                keys, panes = keys[keep], panes[keep]
                batch = batch.filter(keep)
                if batch.n == 0:
                    return
        max_pane = int(panes.max())
        min_pane = int(panes.min())
        self._max_seen_pane = (max_pane if self._max_seen_pane is None
                               else max(self._max_seen_pane, max_pane))
        self._min_seen_pane = (min_pane if self._min_seen_pane is None
                               else min(self._min_seen_pane, min_pane))
        # ring overflow check: two open panes must never share a ring row
        low = (self._fired_boundary - self._window_panes
               if self._fired_boundary is not None else self._min_seen_pane)
        if max_pane - low >= self._ring:
            raise RuntimeError(
                f"pane ring overflow: open span [{low},{max_pane}] exceeds "
                f"ring {self._ring}; increase ring_size or reduce "
                "watermark lag")
        self._note_open_ingest(min_pane)
        self._fold(batch, keys, panes)

    def _note_open_ingest(self, min_pane: int) -> None:
        """Hook: the incremental fire engine invalidates its running
        window accumulators when a batch writes into an already-sealed
        pane (late-but-not-dropped records, or a min-pane decrease)."""
        pass

    # -- firing ------------------------------------------------------------
    def process_watermark(self, watermark: Watermark) -> None:
        self.current_watermark = watermark.timestamp
        self._pre_fire_flush()
        # a window ending at pane boundary p_end fires when
        # wm >= p_end*pane + offset - 1
        wm_pane_end = (watermark.timestamp - self._offset + 1) // self._pane
        if self._max_seen_pane is not None:
            # windows ending at or below min_seen contain no data; never
            # reach below that (their ring rows may alias future panes)
            start = self._min_seen_pane + 1
            if self._fired_boundary is not None:
                start = max(start, self._fired_boundary)
            last = min(wm_pane_end, self._max_seen_pane + self._window_panes)
            for p_end in range(start, last + 1):
                t0 = time.perf_counter()
                self._fire(p_end)
                if (self._record_fire_latency
                        and len(self.fire_latencies_ms) < _MAX_FIRE_SAMPLES):
                    self.fire_latencies_ms.append(
                        (time.perf_counter() - t0) * 1e3)
        # the boundary tracks the watermark even when no data has arrived
        # yet or no window fired, so records behind the watermark are
        # dropped as late exactly like the host operator
        if (self._fired_boundary is None
                or wm_pane_end + 1 > self._fired_boundary):
            self._fired_boundary = wm_pane_end + 1
        self._emit_watermark_out(watermark)

    def _emit_watermark_out(self, watermark: Watermark) -> None:
        """Hook: async-firing operators hold the watermark behind its
        fires' pending emissions so it never overtakes them downstream."""
        self.output.emit_watermark(watermark)

    def _pre_fire_flush(self) -> None:
        pass

    def _fold(self, batch: RecordBatch, keys: np.ndarray,
              panes: np.ndarray) -> None:
        raise NotImplementedError

    def _fire(self, p_end: int) -> None:
        raise NotImplementedError

    @property
    def late_dropped(self) -> int:
        return self._late_dropped
