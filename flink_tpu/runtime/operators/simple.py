"""Stateless and keyed-process operators.

Analogs of StreamMap/StreamFilter/StreamFlatMap
(flink-streaming-java api/operators/Stream{Map,Filter,FlatMap}.java) and
KeyedProcessOperator (api/operators/KeyedProcessOperator). Each prefers the
function's vectorized batch path and falls back to a row loop — chained
vectorized operators later fuse into one XLA program (runtime/compiled.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ...core.elements import Watermark
from ...core.functions import (
    Collector, FilterFunction, FlatMapFunction, MapFunction, ProcessFunction,
    RuntimeContext,
)
from ...core.records import MIN_TIMESTAMP, RecordBatch, Schema
from ..timers import InternalTimerService, Timer
from .base import OneInputOperator, OperatorContext, Output

__all__ = ["MapOperator", "FilterOperator", "FlatMapOperator",
           "KeyedProcessOperator", "BatchFnOperator", "KeyExtractor"]

# KeyExtractor: RecordBatch -> np.ndarray of keys (one per row)
KeyExtractor = Callable[[RecordBatch], np.ndarray]


def _runtime_context(op: OneInputOperator, state_backend=None) -> RuntimeContext:
    ctx = op.ctx
    return RuntimeContext(ctx.task_name, ctx.subtask_index, ctx.parallelism,
                          ctx.max_parallelism, metrics=ctx.metrics,
                          state_backend=state_backend)


class MapOperator(OneInputOperator):
    def __init__(self, fn: MapFunction, out_schema: Optional[Schema] = None,
                 name: str = "Map"):
        super().__init__(name)
        self._fn = fn
        self._out_schema = out_schema

    def open(self) -> None:
        self._fn.open(_runtime_context(self))

    def process_batch(self, batch: RecordBatch) -> None:
        vec = self._fn.map_batch(batch)
        if vec is not None:
            self.output.emit(vec)
            return
        rows = [self._fn.map(r) for r in batch.iter_rows()]
        if not rows:
            return
        schema = self._out_schema
        if schema is None and isinstance(rows[0], tuple) \
                and len(rows[0]) == len(batch.schema) > 1:
            # same-arity tuple output: keep the input's column names so
            # downstream column references (key_by("col")) keep working —
            # from_rows_infer still promotes dtypes per column as needed
            schema = batch.schema
        out, self._out_schema = RecordBatch.from_rows_infer(
            schema, rows, batch.timestamps)
        self.output.emit(out)

    def close(self) -> None:
        self._fn.close()


class FilterOperator(OneInputOperator):
    def __init__(self, fn: FilterFunction, name: str = "Filter"):
        super().__init__(name)
        self._fn = fn

    def open(self) -> None:
        self._fn.open(_runtime_context(self))

    def process_batch(self, batch: RecordBatch) -> None:
        mask = self._fn.filter_batch(batch)
        if mask is None:
            mask = np.fromiter((bool(self._fn.filter(r))
                                for r in batch.iter_rows()),
                               dtype=bool, count=batch.n)
        self.output.emit(batch.filter(mask))

    def close(self) -> None:
        self._fn.close()


class FlatMapOperator(OneInputOperator):
    def __init__(self, fn: FlatMapFunction, out_schema: Optional[Schema] = None,
                 name: str = "FlatMap"):
        super().__init__(name)
        self._fn = fn
        self._out_schema = out_schema

    def open(self) -> None:
        self._fn.open(_runtime_context(self))

    def process_batch(self, batch: RecordBatch) -> None:
        rows: list = []
        ts: list[int] = []
        for i, r in enumerate(batch.iter_rows()):
            t = int(batch.timestamps[i])
            for out in self._fn.flat_map(r):
                rows.append(out)
                ts.append(t)
        if not rows:
            return
        out, self._out_schema = RecordBatch.from_rows_infer(
            self._out_schema, rows, ts)
        self.output.emit(out)

    def close(self) -> None:
        self._fn.close()


class BatchFnOperator(OneInputOperator):
    """Operator over a raw batch->batch callable — the escape hatch the SQL
    layer and compiled segments use."""

    def __init__(self, fn: Callable[[RecordBatch], Optional[RecordBatch]],
                 name: str = "BatchFn", traceable: bool = False):
        super().__init__(name)
        self._fn = fn
        self.traceable = traceable  # True => jax-traceable columnwise fn

    def process_batch(self, batch: RecordBatch) -> None:
        out = self._fn(batch)
        if out is not None and out.n:
            self.output.emit(out)


class KeyedProcessOperator(OneInputOperator):
    """Keyed per-record processing with timers + keyed state
    (reference KeyedProcessOperator). Row-oriented by nature — the user
    function sees one element at a time."""

    def __init__(self, fn: ProcessFunction, key_extractor: KeyExtractor,
                 out_schema: Optional[Schema] = None, name: str = "KeyedProcess"):
        super().__init__(name)
        # per-subtask copy: a shared instance would cross-wire state handles
        # cached in open() across subtasks (reference: functions are
        # serialized per task, RichFunction pattern)
        from ...core.functions import copy_per_subtask
        self._fn = copy_per_subtask(fn)
        self._key_extractor = key_extractor
        self._out_schema = out_schema
        self._backend = None
        self._timers: Optional[InternalTimerService] = None
        self._pending_rows: list = []
        self._pending_ts: list[int] = []

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        self._backend = ctx.create_keyed_backend()
        self._timers = InternalTimerService(
            ctx.key_group_range, ctx.max_parallelism,
            on_event_time=self._fire_timer_event,
            on_processing_time=self._fire_timer_proc)

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        if keyed_snapshots:
            self._backend.restore([s["backend"] for s in keyed_snapshots])
            self._timers.restore([s["timers"] for s in keyed_snapshots])

    def open(self) -> None:
        self._fn.open(_runtime_context(self, self._backend))

    # -- helpers -----------------------------------------------------------
    def _collector(self) -> Collector:
        def sink(value, timestamp):
            self._pending_rows.append(value)
            self._pending_ts.append(
                MIN_TIMESTAMP if timestamp is None else int(timestamp))
        return Collector(sink)

    def _side_collector(self, tag: str, value: Any, timestamp) -> None:
        schema = Schema.infer(value)
        self.output.emit_side(tag, RecordBatch.from_rows(
            schema, [value], [MIN_TIMESTAMP if timestamp is None else timestamp]))

    def _flush_pending(self) -> None:
        if not self._pending_rows:
            return
        out, self._out_schema = RecordBatch.from_rows_infer(
            self._out_schema, self._pending_rows, self._pending_ts)
        self.output.emit(out)
        self._pending_rows, self._pending_ts = [], []

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        keys = self._key_extractor(batch)
        out = self._collector()
        for i, row in enumerate(batch.iter_rows()):
            key = keys[i]
            key = key.item() if isinstance(key, np.generic) else key
            self._backend.set_current_key(key)
            ts = int(batch.timestamps[i])
            ctx = ProcessFunction.Context(
                None if ts == MIN_TIMESTAMP else ts, self._timer_api(key),
                current_key=key, side_collector=self._side_collector)
            self._fn.process_element(batch.row(i), ctx, out)
        self._flush_pending()

    def _timer_api(self, key):
        op = self

        class _TimerApi:
            current_watermark = property(
                lambda s: op._timers.current_watermark)

            def register_event_time_timer(self, ts, namespace=None):
                op._timers.register_event_time_timer(key, ts, namespace)

            def register_processing_time_timer(self, ts, namespace=None):
                op._timers.register_processing_time_timer(key, ts, namespace)

            def delete_event_time_timer(self, ts, namespace=None):
                op._timers.delete_event_time_timer(key, ts, namespace)

            def delete_processing_time_timer(self, ts, namespace=None):
                op._timers.delete_processing_time_timer(key, ts, namespace)

        return _TimerApi()

    def _fire_timer_event(self, timer: Timer) -> None:
        self._fire_timer(timer, "event")

    def _fire_timer_proc(self, timer: Timer) -> None:
        self._fire_timer(timer, "processing")

    def _fire_timer(self, timer: Timer, domain: str) -> None:
        self._backend.set_current_key(timer.key)
        ctx = ProcessFunction.OnTimerContext(
            timer.timestamp, self._timer_api(timer.key), domain, timer.key,
            side_collector=self._side_collector)
        self._fn.on_timer(timer.timestamp, ctx, self._collector())

    def process_watermark(self, watermark: Watermark) -> None:
        self._timers.advance_watermark(watermark.timestamp)
        self._flush_pending()
        super().process_watermark(watermark)

    def advance_processing_time(self, now_ms: int) -> None:
        self._timers.advance_processing_time(now_ms)
        self._flush_pending()

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": self._backend.snapshot(checkpoint_id),
                          "timers": self._timers.snapshot()}}

    def close(self) -> None:
        self._fn.close()
