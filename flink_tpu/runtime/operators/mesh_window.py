"""Mesh slice-window operator: multi-chip execution inside a JobGraph.

This is the deploy seam the reference crosses at Execution.deploy
(flink-runtime executiongraph/Execution.java:511) ->
TaskExecutor.submitTask (taskexecutor/TaskExecutor.java:634), re-thought
for a TPU mesh: instead of N parallel subtasks connected by a hash
repartition over the network, ONE JobGraph vertex executes as an SPMD
program over an n-device `jax.sharding.Mesh`. The keyBy edge into the
vertex is the on-device `all_to_all` exchange (parallel/exchange.py) —
upstream host vertices just hand raw batches to this operator; key-group
routing happens inside the compiled step, riding ICI instead of TCP.

The host side of the operator is only a control plane: it buffers incoming
batches into fixed [D, B] device blocks (static shapes so the step jits
once) and runs the shared pane/watermark protocol (slice_control.py);
fires are one pane-merge program over every shard's key-group range
(WindowOperator.onEventTime:437 / SliceSharedWindowAggProcessor semantics,
vectorized over all keys and all devices).

State checkpointing (VERDICT #2): snapshots materialize per-shard hash
tables + pane accumulators into the SAME key-group-partitioned format the
single-chip TpuKeyedStateBackend emits ({"kind": "tpu", keys, key_groups,
states}), so restore re-filters by the new mesh's shard ranges — a mesh
job can rescale 8->4->8 devices, or hand its state to a single-chip run,
the StateAssignmentOperation/KeyGroupRangeAssignment.java:63 contract.
Key groups are always computed in the job's max-parallelism space, so
mesh and host subtasks agree on ownership.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.keygroups import hash_batch, key_groups_for_hash_batch
from ...core.records import RecordBatch, Schema
from ...ops.hash_table import EMPTY_KEY, lookup_or_insert, make_table
from ...ops.segment_ops import AGG_INITS, make_accumulator
from ...metrics.device import DEVICE_STATS
from ...parallel.mesh import make_mesh, shard_ranges
from ...parallel.sharded_window import (
    AggDef, ShardedWindowAgg, ShardedWindowState,
)
from ...window.assigners import WindowAssigner
from .base import OneInputOperator, OperatorContext, Output
from .device_window import AggSpec
from .slice_control import AsyncFireQueue, SliceControlPlane

__all__ = ["MeshWindowAggOperator"]


@jax.jit
def _probe_program(table: jax.Array, dropped: jax.Array):
    """Pressure scalars: (max per-shard occupancy, total drops)."""
    return ((table != jnp.int64(EMPTY_KEY)).sum(axis=1).max(),
            dropped.sum())


class MeshWindowAggOperator(AsyncFireQueue, SliceControlPlane,
                            OneInputOperator):
    """Keyed slice-window aggregation executed over a device mesh.

    Round 3 (VERDICT r2 weak #5): the fire path matches the single-chip
    operator's standards — ONE fused fire program per window (pane merge +
    emit mask + optional two-phase global top-k + health scalars), results
    materialized with one asynchronous device->host copy instead of
    pulling the full [D, capacity] table, ``async_fire`` holding
    watermarks behind their fires, and pressure checks riding the fire
    outputs instead of a separate sync.
    """

    def __init__(self, assigner: WindowAssigner, key_column: str,
                 aggs: Sequence[AggSpec],
                 n_devices: Optional[int] = None,
                 capacity: int = 1 << 16,
                 ring_size: int = 64,
                 device_batch: int = 1 << 12,
                 emit_window_bounds: bool = True,
                 emit_topk: Optional[int] = None,
                 async_fire: bool = False,
                 fire_incremental: Optional[bool] = None,
                 name: str = "MeshWindowAgg"):
        super().__init__(name)
        pane = assigner.pane_size
        if pane is None:
            raise ValueError(
                "Mesh window operator needs a pane-decomposable assigner "
                "(tumbling, or sliding with size % slide == 0)")
        from ...window.assigners import reject_variable_pane_assigner
        reject_variable_pane_assigner(assigner, "mesh")
        self._assigner = assigner
        self._pane = int(pane)
        self._offset = int(getattr(assigner, "offset", 0))
        size = getattr(assigner, "size", self._pane)
        self._window_panes = int(size) // self._pane
        self._ring = int(ring_size)
        if self._ring < self._window_panes + 1:
            raise ValueError("ring_size must exceed panes per window")
        self._key_column = key_column
        self._aggs = list(aggs)
        self._capacity = capacity
        self._device_batch = int(device_batch)
        self._emit_bounds = emit_window_bounds
        self._topk = emit_topk
        self._async = bool(async_fire)
        self._n_devices = n_devices
        # incremental fire engine (window.fire.incremental): running
        # window accumulators + merge trees, held OUTSIDE
        # ShardedWindowState so snapshots never carry them (derived
        # state; rebuilt from the pane planes after restore/grow)
        self._inc_flag = fire_incremental
        self._inc_enabled = bool(fire_incremental)
        self._inc_next: Optional[int] = None
        self._inc_dirty = True
        self._inc_wins: dict = {}
        self._inc_trees: dict = {}

        self._agg: Optional[ShardedWindowAgg] = None
        self._state: Optional[ShardedWindowState] = None
        # live rescale (PR 12): a pending worker-set change applied at the
        # next barrier-aligned quiescent point; the epoch fences the mesh
        # generation the way the coordinator's execution epoch fences
        # restarts
        self._rescale_request: Optional[int] = None
        self._rescale_epoch = 0
        self._last_rescale_stats: Optional[dict] = None
        self._init_control_plane()
        self._init_async_fires()
        if self._async:
            self._record_fire_latency = False
        self._dropped_seen = 0
        self.stage_s: dict[str, float] = {}
        # non-blocking pressure probe: dispatched at watermark cadence,
        # consumed when its copy lands (never stalls the step pipeline)
        self._probe = None
        self._blocks_since_probe = 0
        # host-side staging buffers for [D, B] blocks
        self._buf_keys: list[np.ndarray] = []
        self._buf_panes: list[np.ndarray] = []
        self._buf_cols: dict[str, list[np.ndarray]] = {}
        self._buf_n = 0

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        # DCN x ICI composition (VERDICT r3 #3): with vertex parallelism
        # P > 1 this subtask owns ctx.key_group_range (the standard keyed
        # exchange delivers only its rows, over TCP when hosts differ) and
        # its LOCAL mesh re-shards that range across this host's devices —
        # DCN between hosts, ICI within the host, per SURVEY §5.8. With
        # P == 1 (single-host mesh vertex) the base is the full key space
        # and behavior is unchanged.
        P = ctx.parallelism
        local = jax.devices()
        n = self._n_devices or (len(local) if P == 1
                                else max(1, len(local) // P))
        self._n_devices = n
        # key groups must live in the job's max-parallelism space so mesh
        # checkpoints interoperate with host subtasks and other mesh sizes
        self._max_parallelism = ctx.max_parallelism
        self._base_range = ctx.key_group_range if P > 1 else None
        base_len = (self._max_parallelism if self._base_range is None
                    else self._base_range.end - self._base_range.start + 1)
        if base_len < n:
            raise ValueError(
                f"subtask key-group range ({base_len} groups) must be >= "
                f"mesh size ({n}); raise pipeline.max-parallelism")
        # single-process multi-host emulation (tests / one-host dev box):
        # when the process sees every host's devices, subtasks take
        # deterministic disjoint slices. On a real multi-host slice each
        # process only sees its own chips and takes them all.
        sub = ctx.subtask_index
        self._parallelism = P
        self._sub_index = sub
        if P > 1 and len(local) >= (sub + 1) * n:
            devs = local[sub * n:(sub + 1) * n]
        else:
            devs = local[:n]
        self._mesh = make_mesh(n, devices=devs)
        if self._inc_flag is None:
            from ...core.config import WindowOptions
            self._inc_enabled = bool(
                ctx.config.get(WindowOptions.FIRE_INCREMENTAL))

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        if not keyed_snapshots:
            return
        self._restore_control_meta([s["meta"] for s in keyed_snapshots])
        self._restore_backends([s["backend"] for s in keyed_snapshots])
        # snapshots never carry the derived incremental planes; the first
        # fire after restore rebuilds them from the pane accumulators
        self._mark_inc_dirty()

    def _mark_inc_dirty(self) -> None:
        self._inc_dirty = True
        self._inc_next = None
        self._inc_wins = {}
        self._inc_trees = {}

    def _note_open_ingest(self, min_pane: int) -> None:
        if self._inc_next is not None and min_pane < self._inc_next - 1:
            self._inc_dirty = True

    # -- agg program construction ------------------------------------------
    def _aggdefs(self, schema: Schema) -> list[AggDef]:
        """AggSpec -> AggDef list. Accumulator dtype follows the input
        column (sum over int64 stays int64, matching the host operator);
        avg accumulates a float sum plane and divides by count at emit."""
        defs = []
        for a in self._aggs:
            if a.kind == "count":
                defs.append(AggDef(a.out_name, "count", jnp.int64))
            elif a.kind == "avg":
                defs.append(AggDef(f"{a.out_name}.sum", "sum", jnp.float32))
            else:
                dt = (jnp.dtype(np.dtype(schema.field(a.field).dtype))
                      if a.field in schema else jnp.dtype(a.dtype))
                defs.append(AggDef(a.out_name, a.kind, dt))
        return defs

    @staticmethod
    def _plane_name(a: AggSpec) -> str:
        return f"{a.out_name}.sum" if a.kind == "avg" else a.out_name

    def _build(self, defs: list[AggDef], capacity: Optional[int] = None
               ) -> None:
        self._agg = ShardedWindowAgg(
            self._mesh, defs, capacity=capacity or self._capacity,
            ring=self._ring, max_parallelism=self._max_parallelism,
            base_range=self._base_range)
        self._state = self._agg.init_state()

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if self._pending:
            self._drain(block=False)
        if batch.n == 0:
            return
        if self._agg is None:
            key_dtype = batch.schema.field(self._key_column).dtype
            if key_dtype is object or not np.issubdtype(np.dtype(key_dtype),
                                                        np.integer):
                raise TypeError(
                    f"mesh window aggregation needs an integer key column; "
                    f"{self._key_column!r} is {key_dtype}")
            self._build(self._aggdefs(batch.schema))
        keys = batch.column(self._key_column).astype(np.int64)
        self._ingest(batch, keys)

    def _fold(self, batch: RecordBatch, keys: np.ndarray,
              panes: np.ndarray) -> None:
        self._buf_keys.append(keys)
        self._buf_panes.append(panes)
        for a in self._aggs:
            if a.kind == "count":
                continue
            self._buf_cols.setdefault(self._plane_name(a), []).append(
                np.asarray(batch.column(a.field)))
        self._buf_n += batch.n
        if self._buf_n >= self._n_devices * self._device_batch:
            self._flush(pad=False)

    def _flush(self, pad: bool) -> None:
        """Drain staged records into [D, B] device steps. With pad=False
        only full D*B blocks run; with pad=True a final padded block
        (valid mask) drains the remainder."""
        if self._agg is None or self._buf_n == 0:
            return
        D, B = self._n_devices, self._device_batch
        full = D * B
        keys = np.concatenate(self._buf_keys)
        panes = np.concatenate(self._buf_panes)
        cols = {n: np.concatenate(vs) for n, vs in self._buf_cols.items()}
        pos, total = 0, len(keys)
        while total - pos >= full:
            self._step_block(keys[pos:pos + full], panes[pos:pos + full],
                             {n: c[pos:pos + full] for n, c in cols.items()},
                             n_valid=full)
            pos += full
        rem = total - pos
        if pad and rem:
            pk = np.zeros(full, np.int64)
            pp = np.zeros(full, np.int64)
            pk[:rem] = keys[pos:]
            pp[:rem] = panes[pos:]
            pc = {}
            for n, c in cols.items():
                buf = np.zeros(full, c.dtype)
                buf[:rem] = c[pos:]
                pc[n] = buf
            self._step_block(pk, pp, pc, n_valid=rem)
            pos = total
        self._buf_keys = [keys[pos:]] if pos < total else []
        self._buf_panes = [panes[pos:]] if pos < total else []
        self._buf_cols = ({n: [c[pos:]] for n, c in cols.items()}
                          if pos < total else {})
        self._buf_n = total - pos

    def _step_block(self, keys: np.ndarray, panes: np.ndarray,
                    cols: dict[str, np.ndarray], n_valid: int) -> None:
        D, B = self._n_devices, self._device_batch
        valid = np.zeros(D * B, bool)
        valid[:n_valid] = True
        dkeys = jnp.asarray(keys.reshape(D, B))
        dpanes = jnp.asarray(panes.reshape(D, B))
        dvalid = jnp.asarray(valid.reshape(D, B))
        dcols = {n: jnp.asarray(c.reshape(D, B)) for n, c in cols.items()}
        self._state, _processed = self._agg.step(
            self._state, dkeys, dcols, dpanes, dvalid)
        self._blocks_since_probe += 1

    # -- firing (fire loop lives in SliceControlPlane) ----------------------
    def _pre_fire_flush(self) -> None:
        self._flush(pad=True)
        self._pressure_probe()

    def _pressure_probe(self) -> None:
        """Proactive growth WITHOUT stalling the pipeline: an async scalar
        probe (max shard occupancy + total drops) is dispatched at
        watermark cadence and consumed whenever its copy has landed; the
        growth decision adds a margin for the blocks dispatched since the
        probe, so the table grows before the load factor bites. Drops are
        still a hard error (also checked on every fire's health scalars)."""
        if self._agg is None:
            return
        if self._probe is not None:
            outs = self._probe
            if all(leaf.is_ready()
                   for leaf in jax.tree_util.tree_leaves(outs)):
                occ, dropped = jax.device_get(outs)
                self._probe = None
                if int(dropped) > self._dropped_seen:
                    raise RuntimeError(
                        f"mesh hash table overflow: {int(dropped)} records "
                        f"dropped (capacity {self._agg.capacity} per "
                        "shard); raise "
                        "state.backend.tpu.slots-per-key-group")
                # blocks dispatched AFTER the probe are invisible to its
                # occupancy: pad the growth decision by what they could add
                margin = self._blocks_since_probe * self._device_batch
                need = int(occ) + margin
                if need > 0.6 * self._agg.capacity:
                    target = self._agg.capacity
                    while need > 0.6 * target:
                        target *= 2
                    self._grow(target)
        if self._probe is None and self._blocks_since_probe:
            outs = _probe_program(self._state.table, self._state.dropped)
            for leaf in jax.tree_util.tree_leaves(outs):
                leaf.copy_to_host_async()
            self._probe = outs
            self._blocks_since_probe = 0

    def _apply_health(self, dropped: int, occ_max: int) -> None:
        """Pressure handling from scalars that rode a fire's outputs —
        the hot loop itself never syncs (matches the single-chip
        apply_health model)."""
        if int(dropped) > self._dropped_seen:
            raise RuntimeError(
                f"mesh hash table overflow: {int(dropped)} records dropped "
                f"(capacity {self._agg.capacity} per shard); raise "
                "state.backend.tpu.slots-per-key-group")
        if int(occ_max) > 0.6 * self._agg.capacity:
            self._grow(self._agg.capacity * 2)

    def _grow(self, new_capacity: int) -> None:
        self._drain(block=True)  # pending fires read the pre-grow state
        snap = self._snapshot_backend()
        defs = list(self._agg.aggs)
        self._build(defs, capacity=new_capacity)
        self._load_snapshot_into_state([snap])
        self._mark_inc_dirty()  # plane shapes changed with capacity

    # -- fire/emit ---------------------------------------------------------
    def _rank_name(self) -> Optional[str]:
        if self._topk is None:
            return None
        return self._plane_name(self._aggs[0])

    def _fire(self, p_end: int) -> None:
        if self._agg is None:
            return
        t_fire = time.perf_counter()
        W = self._window_panes
        # never read panes below min_seen: they hold no data and their ring
        # rows may be occupied by live FUTURE panes (row aliasing)
        first = max(p_end - W, self._min_seen_pane)
        if first >= p_end:
            return
        if self._inc_enabled:
            self._fire_incremental(p_end, first, t_fire)
            return
        rows = [(p % self._ring) for p in range(first, p_end)]
        # constant [W] shape so the fire program compiles once
        pane_rows = np.zeros(W, np.int32)
        pane_rows[:len(rows)] = rows
        rows_valid = np.zeros(W, bool)
        rows_valid[:len(rows)] = True
        outs = self._agg.fire_compact(self._state, pane_rows, rows_valid,
                                      self._rank_name(), self._topk)
        self._enqueue_fire((p_end, outs, None, time.perf_counter()))
        # retire the oldest pane of this window: no future window needs it
        if p_end - W >= self._min_seen_pane:
            self._state = self._agg.retire_row(self._state,
                                               (p_end - W) % self._ring)
        self.stage_s["fire"] = self.stage_s.get("fire", 0.0) + (
            time.perf_counter() - t_fire)

    def _fire_incremental(self, p_end: int, first: int,
                          t_fire: float) -> None:
        """O(capacity) fire: consume the running window view kept by the
        pane-seal programs instead of re-merging all W ring rows. Dirty
        state (restore, grow, boundary jump, write into a sealed pane)
        forces a one-dispatch rebuild from the pane accumulators."""
        from ...metrics.device import DEVICE_STATS

        W, ring = self._window_panes, self._ring
        L = self._agg.tree_size
        rows = [(p % ring) for p in range(first, p_end)]
        sub_row = np.int32((p_end - W) % ring)
        sub_valid = np.bool_(p_end - W >= self._min_seen_pane)
        if (self._inc_dirty or self._inc_next != p_end
                or not (self._inc_wins or self._inc_trees)):
            # padded to [ring] so the rebuild shape is W-independent
            pane_rows = np.zeros(ring, np.int32)
            pane_rows[:len(rows)] = rows
            rows_valid = np.zeros(ring, bool)
            rows_valid[:len(rows)] = True
            pane_leaves = np.full(ring, L, np.int32)
            pane_leaves[:len(rows)] = [p % L for p in range(first, p_end)]
            view, self._inc_wins, self._inc_trees = self._agg.rebuild_inc(
                self._state, pane_rows, rows_valid, pane_leaves,
                sub_row, sub_valid)
            rows_read = sealed = len(rows)
        else:
            view, self._inc_wins, self._inc_trees = self._agg.seal_inc(
                self._state, self._inc_wins, self._inc_trees,
                np.int32((p_end - 1) % ring), sub_row, sub_valid,
                np.int32((p_end - 1) % L), np.int32((p_end - 1 - W) % L))
            rows_read, sealed = (2 if bool(sub_valid) else 1), 1
        outs = self._agg.fire_inc(self._state, view, self._rank_name(),
                                  self._topk)
        DEVICE_STATS.note_panes_sealed(sealed)
        DEVICE_STATS.note_fire_merge_rows(rows_read)
        self._inc_dirty = False
        self._inc_next = p_end + 1
        self._enqueue_fire((p_end, outs, None, time.perf_counter()))
        if p_end - W >= self._min_seen_pane:
            self._state = self._agg.retire_row(self._state,
                                               (p_end - W) % self._ring)
        self.stage_s["fire"] = self.stage_s.get("fire", 0.0) + (
            time.perf_counter() - t_fire)

    def _materialize(self, item: tuple) -> None:
        p_end, outs, _unused, t0 = item
        host = jax.device_get(outs)       # ONE transfer for everything
        if self._topk is not None:
            keys_k, ok, results, dropped, occ = host
            self._apply_health(dropped, occ)
            sel = np.asarray(ok)
            keys = np.asarray(keys_k)[sel]
            res = {n: np.asarray(v)[sel] for n, v in results.items()}
        else:
            table, emit, results, dropped, occ = host
            self._apply_health(dropped, occ)
            mask = np.asarray(emit).reshape(-1)
            idx = np.flatnonzero(mask)
            keys = np.asarray(table).reshape(-1)[idx]
            res = {n: np.asarray(v).reshape(-1)[idx]
                   for n, v in results.items()}
        if len(keys):
            self._emit_rows(p_end, keys, res)
        self._note_latency(t0)

    def _emit_rows(self, p_end: int, keys: np.ndarray, host: dict) -> None:
        count_name = next(a.name for a in self._agg.aggs
                          if a.kind == "count")
        n = len(keys)
        start = (p_end - self._window_panes) * self._pane + self._offset
        end = p_end * self._pane + self._offset
        cols: dict[str, np.ndarray] = {self._key_column: keys}
        fields: list[tuple[str, Any]] = [(self._key_column, np.int64)]
        if self._emit_bounds:
            cols["window_start"] = np.full(n, start, np.int64)
            cols["window_end"] = np.full(n, end, np.int64)
            fields += [("window_start", np.int64), ("window_end", np.int64)]
        for a in self._aggs:
            if a.kind == "avg":
                s = host[f"{a.out_name}.sum"]
                c = np.maximum(host[count_name], 1).astype(s.dtype)
                vals = s / c
            else:
                vals = host[a.out_name]
            cols[a.out_name] = vals
            fields.append((a.out_name, vals.dtype.type))
        schema = Schema(fields)
        ts = np.full(n, end - 1, np.int64)
        self.output.emit(RecordBatch(schema, cols, ts))

    # -- checkpointing ------------------------------------------------------
    def _snapshot_backend(self) -> dict:
        """Key-group-partitioned snapshot, format-compatible with
        TpuKeyedStateBackend.snapshot (state/tpu_backend.py) so mesh and
        single-chip runs restore each other's checkpoints."""
        if self._agg is None:
            return {"kind": "tpu", "keys": np.empty(0, np.int64),
                    "key_groups": np.empty(0, np.int32), "states": {}}
        table = np.asarray(jax.device_get(self._state.table))  # [D, cap]
        host_accs = {n: np.asarray(jax.device_get(v))
                     for n, v in self._state.accs.items()}  # [D, ring, cap]
        keys_parts, group_parts = [], []
        vals_parts: dict[str, list[np.ndarray]] = {
            n: [] for n in host_accs}
        for d in range(self._n_devices):
            occupied = table[d] != np.int64(EMPTY_KEY)
            keys_d = table[d][occupied]
            keys_parts.append(keys_d)
            group_parts.append(key_groups_for_hash_batch(
                hash_batch(keys_d), self._max_parallelism))
            slots = np.flatnonzero(occupied)
            for n, acc in host_accs.items():
                vals_parts[n].append(acc[d][:, slots])
        keys = np.concatenate(keys_parts) if keys_parts else np.empty(
            0, np.int64)
        groups = (np.concatenate(group_parts) if group_parts
                  else np.empty(0, np.int32))
        states = {}
        for a in self._agg.aggs:
            vals = (np.concatenate(vals_parts[a.name], axis=-1)
                    if vals_parts[a.name]
                    else np.empty((self._ring, 0)))
            states[a.name] = {"kind": a.kind,
                              "dtype": str(np.dtype(a.dtype)),
                              "ring": self._ring, "values": vals}
        return {"kind": "tpu", "keys": keys, "key_groups": groups,
                "max_parallelism": self._max_parallelism, "states": states}

    def snapshot_state(self, checkpoint_id: int) -> dict:
        self._flush(pad=True)
        self._drain(block=True)
        snap = {"keyed": {"backend": self._snapshot_backend(),
                          "meta": self._control_meta()}}
        # coordinator-driven live rescale rides the aligned-barrier
        # protocol: the snapshot above IS the consistent point (exactly
        # the reference's savepoint-then-redistribute, minus the restart),
        # so a pending worker-set change applies here, on the mailbox
        # thread, with every buffered row folded and every fire drained
        if self._rescale_request is not None:
            req, self._rescale_request = self._rescale_request, None
            self.rescale_live(req)
        return snap

    # -- live rescale -------------------------------------------------------
    def request_rescale(self, n_devices: int) -> None:
        """Stage a worker-set change; it applies at the next aligned
        barrier (snapshot_state). Thread-safe: a single reference store,
        read once on the mailbox thread."""
        from ...parallel.plan import MESH_RUNTIME
        if not MESH_RUNTIME.rescale_enabled:
            raise RuntimeError(
                "live rescale is disabled (mesh.rescale.enabled=false)")
        self._rescale_request = int(n_devices)

    def rescale_live(self, n_devices: Optional[int] = None,
                     devices: Optional[Sequence] = None) -> dict:
        """Re-shard device-resident key-group state across a new mesh
        WITHOUT restarting the job: snapshot at the quiescent point, diff
        key-group ownership old->new, ship only the pages whose groups
        change owner (checkpoint page format, digest-verified), install on
        the new mesh, and rebuild the derived incremental planes
        (`role="window"` — never shipped). Emits one causal trace tree
        under the ``rescale/`` scope and feeds the migration counters.

        Because every sharded program is cache-keyed by local shard shape
        only (sharded_window.local_signature), a rescale that preserves
        per-device capacity/ring recompiles nothing."""
        from ...metrics.tracing import TRACER
        from ...parallel.rescale import plan_migration, reassemble_pages
        t0 = time.perf_counter()
        old_n = self._n_devices
        local = list(devices) if devices is not None else jax.devices()
        n = int(n_devices) if n_devices else len(local)
        base_len = (self._max_parallelism if self._base_range is None
                    else self._base_range.end - self._base_range.start + 1)
        if base_len < n:
            raise ValueError(
                f"subtask key-group range ({base_len} groups) must be >= "
                f"new mesh size ({n}); raise pipeline.max-parallelism")
        P = getattr(self, "_parallelism", 1)
        sub = getattr(self, "_sub_index", 0)
        if P > 1 and len(local) >= (sub + 1) * n:
            devs = local[sub * n:(sub + 1) * n]
        else:
            devs = local[:n]
        if self._agg is None:
            # nothing materialized yet: adopt the new worker set directly
            self._n_devices = n
            self._mesh = make_mesh(n, devices=devs)
            self._rescale_epoch += 1
            self._last_rescale_stats = {
                "old_devices": old_n, "new_devices": n,
                "keygroups_migrated": 0, "bytes_moved": 0,
                "epoch": self._rescale_epoch, "duration_ms": 0.0}
            return self._last_rescale_stats
        with TRACER.span("rescale", "Rescale") as root:
            root.set_attribute("old_devices", old_n)
            root.set_attribute("new_devices", n)
            # quiescent point: every buffered row folded, every async fire
            # drained — the operator-local equivalent of barrier alignment
            self._flush(pad=True)
            self._drain(block=True)
            old_sig = self._agg.sig
            old_ranges = tuple(self._agg.shard_ranges)
            new_ranges = tuple(shard_ranges(self._max_parallelism, n,
                                            self._base_range))
            snap = self._snapshot_backend()
            with TRACER.span("rescale", "Migrate") as mig:
                plan = plan_migration(snap, old_ranges, new_ranges)
                verified = reassemble_pages(plan.pages, snap)
                mig.set_attribute("keygroups_migrated",
                                  plan.keygroups_migrated)
                mig.set_attribute("bytes_moved", plan.bytes_moved)
                mig.set_attribute("pages_moved", len(plan.moved_pages))
            with TRACER.span("rescale", "Rebuild") as reb:
                self._n_devices = n
                self._mesh = make_mesh(n, devices=devs)
                # never shrink per-shard capacity on rescale: keeping the
                # local shard signature stable is what lets the program
                # caches hit (recompiles == 0 across the switch)
                self._capacity = max(self._capacity, self._agg.capacity)
                if len(verified["keys"]) or verified["states"]:
                    self._restore_backends([verified])
                else:
                    self._build(list(self._agg.aggs),
                                capacity=self._agg.capacity)
                # derived incremental planes are rebuilt, never shipped
                self._mark_inc_dirty()
                reb.set_attribute("local_shapes_changed",
                                  self._agg.sig != old_sig)
            self._rescale_epoch += 1
            root.set_attribute("epoch", self._rescale_epoch)
        duration_ms = (time.perf_counter() - t0) * 1e3
        DEVICE_STATS.note_rescale(plan.keygroups_migrated,
                                  plan.bytes_moved, duration_ms)
        self._last_rescale_stats = {
            "old_devices": old_n, "new_devices": n,
            "keygroups_migrated": plan.keygroups_migrated,
            "bytes_moved": plan.bytes_moved,
            "epoch": self._rescale_epoch,
            "duration_ms": duration_ms}
        return self._last_rescale_stats

    def _live_pane_span(self) -> range:
        """Panes whose ring rows may hold live data (everything below has
        been retired/zeroed)."""
        if self._max_seen_pane is None:
            return range(0)
        first = self._min_seen_pane
        if self._fired_boundary is not None:
            first = max(first, self._fired_boundary - self._window_panes)
        return range(first, self._max_seen_pane + 1)

    def _remap_ring_rows(self, vals: np.ndarray, old_ring: int,
                         kind: str, dtype) -> np.ndarray:
        """Re-seat restored [old_ring, N] accumulator rows onto this
        operator's ring: live panes move row (p % old_ring) ->
        (p % new_ring); retired rows are the aggregate identity."""
        if old_ring == self._ring:
            return vals
        span = self._live_pane_span()
        if len(span) > self._ring:
            raise RuntimeError(
                f"cannot restore onto ring {self._ring}: {len(span)} panes "
                "are live; increase ring_size")
        identity = np.asarray(jax.device_get(AGG_INITS[kind](
            jnp.dtype(dtype))))
        out = np.full((self._ring, vals.shape[1]), identity,
                      dtype=vals.dtype)
        for p in span:
            out[p % self._ring] = vals[p % old_ring]
        return out

    def _restore_backends(self, snaps: list[dict]) -> None:
        snaps = [s for s in snaps if len(s.get("keys", ()))
                 or s.get("states")]
        if not snaps:
            return
        # agg program config comes from the snapshot itself (schema not yet
        # seen at restore time), like the reference rebuilding serializers
        # from their snapshots
        meta = {}
        for s in snaps:
            meta.update(s["states"])
        defs = [AggDef(n, m["kind"], jnp.dtype(m["dtype"]))
                for n, m in meta.items()]
        # capacity: smallest power of two giving every shard 2x headroom
        n_keys = sum(len(s["keys"]) for s in snaps)
        per_shard = max(1, (2 * n_keys) // self._n_devices)
        cap = self._capacity
        while cap < per_shard:
            cap <<= 1
        self._build(defs, capacity=cap)
        self._load_snapshot_into_state(snaps)

    def _load_snapshot_into_state(self, snaps: list[dict]) -> None:
        """Filter restored keys into each shard's key-group range and
        rebuild per-shard tables + accumulators (the
        StateAssignmentOperation re-distribution step)."""
        all_keys = np.concatenate(
            [np.asarray(s["keys"], np.int64) for s in snaps])
        all_groups = np.concatenate(
            [np.asarray(s["key_groups"], np.int32) for s in snaps])
        vals: dict[str, np.ndarray] = {}
        for a in self._agg.aggs:
            parts = []
            for s in snaps:
                sd = s.get("states", {}).get(a.name)
                if sd is None:
                    continue
                parts.append(self._remap_ring_rows(
                    np.asarray(sd["values"]), int(sd["ring"]),
                    a.kind, a.dtype))
            vals[a.name] = (np.concatenate(parts, axis=-1) if parts
                            else np.empty((self._ring, 0)))
        D, cap, ring = self._n_devices, self._agg.capacity, self._ring
        tables = np.empty((D, cap), np.int64)
        accs = {a.name: np.empty((D, ring, cap),
                                 np.dtype(jnp.dtype(a.dtype).name))
                for a in self._agg.aggs}
        for d, rng in enumerate(self._agg.shard_ranges):
            sel = (all_groups >= rng.start) & (all_groups <= rng.end)
            keys_d = all_keys[sel]
            table_d = make_table(cap)
            if len(keys_d):
                table_d, slots, ok = lookup_or_insert(
                    table_d, jnp.asarray(keys_d))
                if not bool(jax.device_get(ok.all())):
                    raise RuntimeError(
                        "mesh restore overflow: raise capacity")
            tables[d] = np.asarray(jax.device_get(table_d))
            for a in self._agg.aggs:
                acc = np.array(jax.device_get(make_accumulator(
                    a.kind, (ring, cap), a.dtype)))
                if len(keys_d):
                    acc[:, np.asarray(jax.device_get(slots))] = \
                        vals[a.name][:, sel]
                accs[a.name][d] = acc
        sharding = self._agg._sharding
        self._state = ShardedWindowState(
            table=jax.device_put(jnp.asarray(tables), sharding),
            accs={n: jax.device_put(jnp.asarray(v), sharding)
                  for n, v in accs.items()},
            dropped=jax.device_put(jnp.zeros(D, jnp.int64), sharding))

    # -- teardown ----------------------------------------------------------
    def finish(self) -> None:
        self._flush(pad=True)
        self._drain(block=True)
