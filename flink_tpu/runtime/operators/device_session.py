"""Device session windows: merging windows on per-key session LANES.

The reference runs sessions through the generic WindowOperator with a
MergingWindowSet (flink-streaming-java runtime/operators/windowing/
MergingWindowSet.java, WindowOperator.java:98): one state namespace per
window, merged pairwise as elements arrive. That design is per-record and
per-window-object — the opposite of what a TPU wants.

This operator keeps the SURVEY §7 split: the host runs only the watermark
protocol; gap/merge logic AND the per-session accumulators live on device
in dense planes. The layout mirrors the slice-window pane ring: every key
slot owns L session *lanes* ([L, capacity] planes for start/end/open +
one per aggregate), and a key's live sessions rotate through its lanes
the way panes rotate through ring rows.

Per micro-batch, ONE fused program:
  * events arrive sorted by (key, ts) (host numpy lexsort);
  * hash-table lookup-or-insert -> key slot;
  * session segmentation: an event merges into a lane it overlaps within
    ``gap`` (all L lanes are checked), successive in-batch events split
    where ts gaps exceed ``gap``; new segments allocate the next lane;
  * one scatter-fold per aggregate into (lane, slot), start folds MIN,
    end folds MAX — so a merging event EXTENDS its session in place;
  * the key's current-lane pointer updates to its last event's lane.

A session window [start, last_ts + gap) fires when the watermark passes
its end, as one compiled scan over the [L, capacity] planes that
compacts (key, start, end, aggregates) and resets fired lanes.

Segments only bypass the lanes into the host pending buffer once they
are SETTLED — no event that is still non-late could merge into them
(end + 2*gap behind the fired boundary); anything fresher keeps a lane,
where out-of-order events find it through the all-lanes merge probe.

Semantics vs the host operator (exact for in-order input and for
arbitrary NON-late disorder, except the bridge case below):
  * allowed_lateness = 0: an event whose merged window would end at or
    behind the fired boundary is dropped and counted, like the device
    pane operator;
  * an event bridging TWO open sessions of one key joins one of them;
    the host MergingWindowSet would fuse both into a single window. This
    needs per-key disorder > gap to arise; such streams belong on the
    host operator (the planner default for merging windows).
  * more than L concurrently-open sessions per key (watermark lag >
    ~L * gap) raises at the next watermark instead of corrupting state.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.elements import Watermark
from ...core.records import RecordBatch, Schema
from ...metrics.device import DEVICE_STATS, instrumented_program_cache, \
    pytree_nbytes
from ...ops.hash_table import EMPTY_KEY, lookup_or_insert, \
    sanitize_keys_device
from ...ops.segment_ops import pow2_ceil
from ...state.tpu_backend import TpuKeyedStateBackend
from .base import OneInputOperator, OperatorContext, Output
from .device_window import AggSpec

__all__ = ["DeviceSessionWindowOperator"]

_NEG = np.int64(-(1 << 62))
_POS = np.int64(1 << 62)


@instrumented_program_cache("device_session.step", maxsize=64)
def _sess_step(fold_sig: tuple, lanes: int, gap: int, dirty_block: int):
    """One fused program per batch. ``fold_sig``: (kind, name, field)."""
    from ...ops.segment_ops import scatter_fold

    L = lanes
    donate = (0, 1, 2, 3, 4, 5)

    @partial(jax.jit, donate_argnums=donate)
    def step(table, planes, cur_lane, dropped, late, dirty, keys, ts, cols,
             n_valid, fired_boundary):
        B = keys.shape[0]
        cap = cur_lane.shape[0]
        in_batch = jnp.arange(B) < n_valid
        keys = sanitize_keys_device(keys)
        table, kslot, ok = lookup_or_insert(table, keys, in_batch)
        valid = ok & in_batch
        dropped = dropped + jnp.sum(in_batch & ~ok).astype(jnp.int64)
        gs = jnp.maximum(kslot, 0)
        # first occurrence per key slot in this (sorted) batch
        widx0 = jnp.where(valid, kslot, cap).astype(jnp.int32)
        firstpos = jnp.full(cap + 1, B, jnp.int32).at[widx0].min(
            jnp.arange(B, dtype=jnp.int32))
        first = valid & (jnp.arange(B, dtype=jnp.int32) == firstpos[widx0])
        # merge check against ALL open lanes of the key (L gathers)
        mergeable = []
        for lane in range(L):
            s = planes["__start__"][lane, gs]
            e = planes["__end__"][lane, gs]
            o = planes["__open__"][lane, gs] > 0
            # strict overlap, like TimeWindow.intersects: [ts, ts+gap)
            # meets [s, e+gap) iff ts < e+gap and s < ts+gap
            mergeable.append(o & (ts > s - gap) & (ts < e + gap))
        mg = jnp.stack(mergeable, axis=1)              # [B, L]
        can_merge = mg.any(axis=1)
        merge_lane = jnp.argmax(mg, axis=1).astype(jnp.int32)
        # late (allowed_lateness=0, like the host operator): the event's
        # own window [ts, ts+gap) closed already and no open session can
        # absorb it. Segment followers of a LIVE anchor are never late
        # (sorted order: their ts >= the anchor's, whose window is open).
        is_late = valid & ~can_merge & (ts + gap <= fired_boundary)
        late = late + jnp.sum(is_late).astype(jnp.int64)
        valid = valid & ~is_late
        # anchors: key-first, an in-batch ts jump > gap (sorted by
        # (key, ts), prev row is the predecessor), or the first survivor
        # after a late-dropped predecessor (it must re-decide its lane)
        prev_ts = jnp.concatenate([ts[:1], ts[:-1]])
        prev_same = jnp.concatenate(
            [jnp.zeros(1, bool), (keys[1:] == keys[:-1])]) & ~first
        prev_late = jnp.concatenate([jnp.zeros(1, bool), is_late[:-1]])
        in_jump = prev_same & ((ts - prev_ts >= gap) | prev_late)
        is_anchor = valid & (first | in_jump)
        # ---- two-level fold: events -> per-SEGMENT accumulators --------
        # every anchor opens a batch-local segment; events fold into [B]
        # segment buffers first. Only SETTLED segments (no non-late event
        # can still merge into them; see the classification below) bypass
        # the lanes into the pending-emission buffers — every other
        # segment takes a lane, so a key may allocate SEVERAL lanes per
        # batch and `lanes` must cover its maximum concurrently-open
        # (unsettled) sessions.
        idx = jnp.arange(B, dtype=jnp.int32)
        last_anchor = jax.lax.cummax(jnp.where(is_anchor, idx, -1))
        seg_ok = valid & (last_anchor >= 0)
        seg_id = jnp.where(seg_ok, last_anchor, B).astype(jnp.int32)
        sstart = jnp.full(B + 1, jnp.iinfo(jnp.int64).max,
                          jnp.int64).at[seg_id].min(ts, mode="drop")[:B]
        send = jnp.full(B + 1, jnp.iinfo(jnp.int64).min,
                        jnp.int64).at[seg_id].max(ts, mode="drop")[:B]
        scount = jnp.zeros(B + 1, jnp.int64).at[seg_id].add(
            1, mode="drop")[:B]
        svals = {}
        for kind, name, field in fold_sig:
            v = cols[field].astype(planes[name].dtype)
            if kind == "sum":
                buf = jnp.zeros(B + 1, v.dtype).at[seg_id].add(
                    v, mode="drop")
            elif kind == "min":
                buf = jnp.full(B + 1, AGG_IDENT_MAX(v.dtype),
                               v.dtype).at[seg_id].min(v, mode="drop")
            else:
                buf = jnp.full(B + 1, AGG_IDENT_MIN(v.dtype),
                               v.dtype).at[seg_id].max(v, mode="drop")
            svals[name] = buf[:B]
        # segment metadata lives at the anchor's row index
        seg_here = is_anchor                        # this row IS a segment
        skslot = kslot                              # at anchor rows
        skey = keys
        smerge = can_merge & seg_here
        smlane = merge_lane
        # is this segment its key's LAST in the batch?
        lastseg = jnp.full(cap + 1, -1, jnp.int32).at[
            jnp.where(seg_here, kslot, cap).astype(jnp.int32)].max(idx)
        seg_is_last = jnp.asarray(seg_here & (idx == lastseg[widx0]))
        # classify: a segment bypasses the lanes ONLY when it is SETTLED —
        # every event that could still merge into it (ts < end + gap and
        # within gap of it) is already late (ts + gap <= fired_boundary),
        # i.e. end + 2*gap <= fired_boundary. Eagerly finalizing merely
        # gap-closed-IN-BATCH segments (the old rule) split sessions for
        # out-of-order but NON-late events: the segment sat in the host
        # pending buffer where no later event could reach it (ADVICE r4
        # medium). Unsettled middle segments now take lanes too.
        settled = send + jnp.int64(2 * gap) <= fired_boundary
        seg_to_lane = seg_here & (smerge | seg_is_last | ~settled)
        seg_emit = seg_here & ~smerge & ~seg_is_last & settled
        # lane allocation, j-th free lane for a key's j-th new segment
        # (sorted batch => a key's segments are contiguous; their ordinals
        # index into the key's free-lane rotation, so several unsettled
        # segments of one key land on distinct lanes in one batch)
        need_alloc = seg_to_lane & ~smerge
        cs = jnp.cumsum(need_alloc.astype(jnp.int32))
        base = jnp.zeros(cap + 1, jnp.int32).at[
            jnp.where(first, kslot, cap).astype(jnp.int32)].max(
            cs - need_alloc.astype(jnp.int32), mode="drop")
        ordn = jnp.where(need_alloc, cs - base[widx0] - 1, 0)
        cl = cur_lane[gs]
        open_bl = jnp.stack([planes["__open__"][ln, gs] > 0
                             for ln in range(L)], axis=1)     # [B, L]
        rot = (cl[:, None] + 1
               + jnp.arange(L, dtype=jnp.int32)[None, :]) % L
        rot_free = ~jnp.take_along_axis(open_bl, rot, axis=1)
        free_rank = jnp.cumsum(rot_free.astype(jnp.int32), axis=1)
        pick = rot_free & (free_rank == (ordn + 1)[:, None])
        alloc_lane = jnp.take_along_axis(
            rot, jnp.argmax(pick, axis=1)[:, None], axis=1)[:, 0]
        no_free = need_alloc & ~pick.any(axis=1)
        overflow = jnp.sum(no_free).astype(jnp.int64)
        dropped = dropped + overflow
        seg_to_lane = seg_to_lane & ~no_free
        lane_t = jnp.where(smerge, smlane, alloc_lane).astype(jnp.int32)
        # ---- fold surviving segment TOTALS into lanes ------------------
        flat = lane_t * cap + gs.astype(jnp.int32)
        sel = seg_to_lane
        out = dict(planes)
        out["__start__"] = scatter_fold(
            "min", planes["__start__"].reshape(-1), flat, sstart,
            sel).reshape(L, cap)
        out["__end__"] = scatter_fold(
            "max", planes["__end__"].reshape(-1), flat, send,
            sel).reshape(L, cap)
        out["__open__"] = planes["__open__"].reshape(-1).at[
            jnp.where(sel, flat, L * cap)].max(
            jnp.int8(1), mode="drop").reshape(L, cap)
        out["__count__"] = scatter_fold(
            "count", planes["__count__"].reshape(-1), flat, scount,
            sel).reshape(L, cap)
        for kind, name, _field in fold_sig:
            out[name] = scatter_fold(
                kind, planes[name].reshape(-1), flat, svals[name],
                sel).reshape(L, cap)
        # cur_lane := lane of the key's last segment (when it got a lane)
        cur_lane = cur_lane.at[
            jnp.where(seg_is_last & seg_to_lane, kslot, cap)
            .astype(jnp.int32)].set(lane_t, mode="drop")
        dirty = dirty.at[gs // dirty_block].set(True)
        # ---- compact gap-closed segments for host-side pending emit ----
        pos = jnp.cumsum(seg_emit.astype(jnp.int32)) - 1
        tgt = jnp.where(seg_emit, pos, B)
        n_emit = jnp.sum(seg_emit.astype(jnp.int64))
        ekey = jnp.zeros(B, jnp.int64).at[tgt].set(skey, mode="drop")
        estart = jnp.zeros(B, jnp.int64).at[tgt].set(sstart, mode="drop")
        eend = jnp.zeros(B, jnp.int64).at[tgt].set(send, mode="drop")
        ecount = jnp.zeros(B, jnp.int64).at[tgt].set(scount, mode="drop")
        evals = {name: jnp.zeros(B, svals[name].dtype).at[tgt].set(
            svals[name], mode="drop") for name in svals}
        return (table, out, cur_lane, dropped, late, dirty,
                n_emit, ekey, estart, eend, ecount, evals)

    return step


def AGG_IDENT_MAX(dtype):
    return (jnp.inf if jnp.issubdtype(dtype, jnp.floating)
            else jnp.iinfo(dtype).max)


def AGG_IDENT_MIN(dtype):
    return (-jnp.inf if jnp.issubdtype(dtype, jnp.floating)
            else jnp.iinfo(dtype).min)


@instrumented_program_cache("device_session.fire", maxsize=64)
def _sess_fire(agg_sig: tuple, lanes: int, gap: int):
    """Fire scan: compact every open session with end + gap <= boundary
    into [capacity]-bounded buffers and reset its lane. Returns the new
    planes, the fired count, and an overflow count (fired sessions beyond
    the buffer stay open for the next scan — the host loops)."""

    @jax.jit
    def fire(table, planes, boundary):
        L, cap = planes["__open__"].shape
        end = planes["__end__"]
        fire_mask = ((planes["__open__"] > 0)
                     & (end + gap <= boundary)).reshape(-1)
        flat_slot = jnp.tile(jnp.arange(cap), L)
        keys_flat = jnp.tile(table, L)
        pos = jnp.cumsum(fire_mask.astype(jnp.int32)) - 1
        n_fired = jnp.sum(fire_mask.astype(jnp.int64))
        can = fire_mask & (pos < cap)
        overflow = n_fired - jnp.sum(can.astype(jnp.int64))
        tgt = jnp.where(can, pos, cap)
        out_keys = jnp.zeros(cap, jnp.int64).at[tgt].set(
            keys_flat, mode="drop")
        out_start = jnp.zeros(cap, jnp.int64).at[tgt].set(
            planes["__start__"].reshape(-1), mode="drop")
        out_end = jnp.zeros(cap, jnp.int64).at[tgt].set(
            planes["__end__"].reshape(-1), mode="drop")
        outs = {}
        count_flat = planes["__count__"].reshape(-1)
        out_count = jnp.zeros(cap, jnp.int64).at[tgt].set(
            count_flat, mode="drop")
        for kind, out_name, plane in agg_sig:
            if kind == "count":
                outs[out_name] = out_count
            elif kind == "avg":
                s = jnp.zeros(cap, planes[plane].dtype).at[tgt].set(
                    planes[plane].reshape(-1), mode="drop")
                outs[out_name] = s / jnp.maximum(out_count, 1).astype(
                    s.dtype)
            else:
                outs[out_name] = jnp.zeros(
                    cap, planes[plane].dtype).at[tgt].set(
                    planes[plane].reshape(-1), mode="drop")
        # reset fired lanes (only those that fit the buffer this pass)
        new = dict(planes)
        rs = can.reshape(L, cap)
        new["__open__"] = jnp.where(rs, jnp.int8(0), planes["__open__"])
        # reset to the SAME identities register_array_state starts with
        new["__start__"] = jnp.where(rs, jnp.iinfo(jnp.int64).max,
                                     planes["__start__"])
        new["__end__"] = jnp.where(rs, jnp.iinfo(jnp.int64).min,
                                   planes["__end__"])
        new["__count__"] = jnp.where(rs, 0, planes["__count__"])
        for kind, _o, plane in agg_sig:
            if kind == "count":
                continue
            arr = planes[plane]
            if kind == "min":
                ident = (jnp.inf if jnp.issubdtype(arr.dtype, jnp.floating)
                         else jnp.iinfo(arr.dtype).max)
            elif kind == "max":
                ident = (-jnp.inf
                         if jnp.issubdtype(arr.dtype, jnp.floating)
                         else jnp.iinfo(arr.dtype).min)
            else:
                ident = 0
            new[plane] = jnp.where(rs, jnp.asarray(ident, arr.dtype), arr)
        fired = jnp.minimum(n_fired, jnp.int64(cap))
        return new, out_keys, out_start, out_end, outs, fired, overflow

    return fire


class DeviceSessionWindowOperator(OneInputOperator):
    def __init__(self, gap_ms: int, key_column: str,
                 aggs: Sequence[AggSpec],
                 capacity: int = 1 << 16,
                 lanes: int = 4,
                 emit_window_bounds: bool = True,
                 name: str = "DeviceSessionWindowAgg"):
        super().__init__(name)
        self._gap = int(gap_ms)
        self._lanes = int(lanes)
        self._key_column = key_column
        self._aggs = list(aggs)
        self._capacity = capacity
        self._emit_bounds = emit_window_bounds
        self._backend: Optional[TpuKeyedStateBackend] = None
        self._registered = False
        self._late_dropped = 0
        self._late_cached = 0
        self._fired_boundary = _NEG
        self.fire_latencies_ms: list[float] = []
        self.stage_s = {"ingest": 0.0, "fire": 0.0, "drain": 0.0}
        # gap-closed sessions awaiting their watermark, as columnar numpy
        # chunks {"k","s","e","c", aggs...} (filled by the step's eager
        # in-batch finalization; emitted once the watermark passes)
        self._pending: list[dict] = []

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        # host_index=False: the fused session program inserts into the
        # table with the XLA probe itself; the native dense-slot allocator
        # must not also hand out slots for this table (a restored key
        # would sit at a dense slot the probe never visits)
        self._backend = TpuKeyedStateBackend(
            ctx.key_group_range, ctx.max_parallelism,
            capacity=self._capacity, host_index=False)
        L = self._lanes
        self._backend.register_array_state("__start__", "min", jnp.int64,
                                           ring=L)
        self._backend.register_array_state("__end__", "max", jnp.int64,
                                           ring=L)
        self._backend.register_array_state("__open__", "max", jnp.int8,
                                           ring=L)
        self._backend.register_array_state("__count__", "count", jnp.int64,
                                           ring=L)
        self._backend.register_array_state("__cur_lane__", "sum", jnp.int32)
        self._late_dev = jnp.zeros((), jnp.int64)

    def _register_aggs(self, schema: Schema) -> None:
        for a in self._aggs:
            if a.field is not None and a.field in schema:
                col_dtype = np.dtype(schema.field(a.field).dtype)
                a.dtype = (jnp.float32 if a.kind == "avg"
                           else jnp.dtype(col_dtype))
            if a.kind == "avg":
                self._backend.register_array_state(
                    f"{a.out_name}.sum", "sum", a.dtype, ring=self._lanes)
            elif a.kind != "count":
                self._backend.register_array_state(
                    a.out_name, a.kind, a.dtype, ring=self._lanes)
        self._registered = True

    def _fold_sig(self) -> tuple:
        sig = []
        for a in self._aggs:
            if a.kind == "count":
                continue
            name = f"{a.out_name}.sum" if a.kind == "avg" else a.out_name
            sig.append(("sum" if a.kind == "avg" else a.kind, name,
                        a.field))
        return tuple(sig)

    def _agg_sig(self) -> tuple:
        sig = []
        for a in self._aggs:
            plane = (f"{a.out_name}.sum" if a.kind == "avg"
                     else "__count__" if a.kind == "count" else a.out_name)
            sig.append((a.kind, a.out_name, plane))
        return tuple(sig)

    def _plane_names(self) -> list[str]:
        names = ["__start__", "__end__", "__open__", "__count__"]
        names += [n for _k, n, _f in self._fold_sig()]
        return names

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if not self._registered:
            key_dtype = batch.schema.field(self._key_column).dtype
            if key_dtype is object or not np.issubdtype(
                    np.dtype(key_dtype), np.integer):
                raise TypeError(
                    "device session windows need an integer key column; "
                    f"{self._key_column!r} is {key_dtype}")
            self._register_aggs(batch.schema)
        t0 = time.perf_counter()
        keys = np.asarray(batch.column(self._key_column)).astype(np.int64)
        ts = np.asarray(batch.timestamps, np.int64)
        order = np.lexsort((ts, keys))
        n = batch.n
        P = pow2_ceil(n)

        def pad(a, fill=0):
            a = a[order]
            if P == n:
                return a
            return np.concatenate([a, np.full(P - n, fill, a.dtype)])

        sig = self._fold_sig()
        from ..watchdog import stall_bounded

        def upload():
            return ({f: jnp.asarray(pad(np.asarray(batch.column(f))))
                     for _k, _n, f in sig},
                    jnp.asarray(pad(keys)), jnp.asarray(pad(ts, _NEG)))

        # deadline-bounded sites (docs/ROBUSTNESS.md): the upload and the
        # materialization are idempotent (stall-retried in place); the
        # step dispatch visits its fault site INSIDE the supervised call,
        # so an injected hang abandoned by the watchdog never reaches the
        # donating program (exactly-once under stall-retry)
        cols, dkeys, dts = stall_bounded("transfer.h2d", upload,
                                         scope="device_session")
        DEVICE_STATS.note_h2d(
            pytree_nbytes(cols) + dkeys.nbytes + dts.nbytes, n)

        def dispatch():
            step = _sess_step(sig, self._lanes, self._gap,
                              self._backend.dirty_block_size)
            planes = {n_: self._backend.get_array(n_)
                      for n_ in self._plane_names()}
            return step(
                self._backend.table, planes,
                self._backend.get_array("__cur_lane__"),
                self._backend.dropped_device, self._late_dev,
                self._backend.dirty_mask,
                dkeys, dts, cols,
                np.int64(n), np.int64(self._fired_boundary))

        (table, out, cur_lane, dropped, late, dirty,
         n_emit, ekey, estart, eend, ecount, evals) = stall_bounded(
            "device.execute", dispatch, scope="device_session")
        self._backend.table = table
        for n_, arr in out.items():
            self._backend.set_array(n_, arr)
        self._backend.set_array("__cur_lane__", cur_lane)
        self._backend._dropped = dropped
        # lint: sync-ok emitted-count gate per batch; bounds the d2h slice
        g = int(jax.device_get(n_emit))
        if g:
            span = min(pow2_ceil(g), P)
            host = stall_bounded(
                "transfer.d2h",
                # lint: sync-ok session emit drain, one d2h per emitting batch
                lambda: jax.device_get(
                    {"k": ekey[:span], "s": estart[:span],
                     "e": eend[:span], "c": ecount[:span],
                     "v": {n_: v[:span] for n_, v in evals.items()}}),
                scope="device_session")
            DEVICE_STATS.note_d2h(pytree_nbytes(host), g)
            chunk = {kk: np.asarray(vv)[:g] for kk, vv in host.items()
                     if kk != "v"}
            for n_, v in host["v"].items():
                chunk[n_] = np.asarray(v)[:g]
            self._pending.append(chunk)
        self._late_dev = late
        self._backend.set_dirty_mask(dirty)
        self.stage_s["ingest"] += time.perf_counter() - t0

    def process_watermark(self, watermark: Watermark) -> None:
        self.current_watermark = watermark.timestamp
        boundary = watermark.timestamp + 1
        if boundary > self._fired_boundary:
            self._fired_boundary = boundary
            self._fire(boundary)
            self._flush_pending(boundary)
        self.output.emit_watermark(watermark)

    def _flush_pending(self, boundary: int) -> None:
        """Emit eagerly-finalized (gap-closed in batch) sessions whose
        window end passed the watermark; keep the rest."""
        if not self._pending:
            return
        merged: dict = {}
        for key in self._pending[0]:
            merged[key] = np.concatenate([c[key] for c in self._pending])
        ripe = merged["e"] + self._gap <= boundary
        if ripe.any():
            sel = {k: v[ripe] for k, v in merged.items()}
            outs = {}
            for a in self._aggs:
                if a.kind == "count":
                    outs[a.out_name] = sel["c"]
                elif a.kind == "avg":
                    s = sel[f"{a.out_name}.sum"]
                    outs[a.out_name] = s / np.maximum(
                        sel["c"], 1).astype(s.dtype)
                else:
                    outs[a.out_name] = sel[a.out_name]
            self._emit({"k": sel["k"], "s": sel["s"], "e": sel["e"],
                        "o": outs}, int(ripe.sum()))
        rest = ~ripe
        if rest.any():
            self._pending = [{k: v[rest] for k, v in merged.items()}]
        else:
            self._pending = []

    def _fire(self, boundary: int) -> None:
        if not self._registered:
            return
        t0 = time.perf_counter()
        from ..watchdog import stall_bounded
        fire = _sess_fire(self._agg_sig(), self._lanes, self._gap)
        while True:
            planes = {n_: self._backend.get_array(n_)
                      for n_ in self._plane_names()}
            # each fire dispatch is a deadline-bounded device.execute
            # visit (hang trips abandoned by the watchdog never reach
            # the program; a stalled dispatch retries once, then fails
            # the task into restart-from-checkpoint)
            new, keys, start, end, outs, fired, overflow = stall_bounded(
                "device.execute",
                lambda: fire(self._backend.table, planes,
                             np.int64(boundary)),
                scope="device_session")
            # lint: sync-ok fire loop control (fired/overflow counts)
            fired_h, overflow_h = map(int, jax.device_get(
                (fired, overflow)))
            if fired_h == 0:
                break
            for n_, arr in new.items():
                self._backend.set_array(n_, arr)
            span = min(pow2_ceil(fired_h), self._backend.capacity)
            host = stall_bounded(
                "transfer.d2h",
                # lint: sync-ok session fire drain, one d2h per fire round
                lambda: jax.device_get(
                    {"k": keys[:span], "s": start[:span], "e": end[:span],
                     "o": {n_: v[:span] for n_, v in outs.items()}}),
                scope="device_session")
            DEVICE_STATS.note_d2h(pytree_nbytes(host), fired_h)
            self._emit(host, fired_h)
            if overflow_h == 0:
                break
        # deferred health: table overflow / lane collisions raise here
        self._refresh_late()
        # lint: sync-ok deferred overflow health check, once per fire
        dropped = int(jax.device_get(self._backend.dropped_device))
        if dropped:
            raise RuntimeError(
                f"device session state overflow: {dropped} records hit "
                f"hash-table or session-lane limits; raise capacity/"
                f"lanes (lanes={self._lanes})")
        ms = (time.perf_counter() - t0) * 1e3
        if len(self.fire_latencies_ms) < 65536:
            self.fire_latencies_ms.append(ms)
        self.stage_s["fire"] += ms / 1e3

    def _emit(self, host: dict, n: int) -> None:
        keys = np.asarray(host["k"])[:n]
        start = np.asarray(host["s"])[:n]
        end = np.asarray(host["e"])[:n] + self._gap
        cols: dict[str, np.ndarray] = {self._key_column: keys}
        fields: list = [(self._key_column, np.int64)]
        if self._emit_bounds:
            cols["window_start"] = start
            cols["window_end"] = end
            fields += [("window_start", np.int64),
                       ("window_end", np.int64)]
        # iterate AggSpec order, not dict order: device_get round-trips
        # JAX pytree dicts in SORTED-key order
        for a in self._aggs:
            v = np.asarray(host["o"][a.out_name])[:n]
            cols[a.out_name] = v
            fields.append((a.out_name, v.dtype.type))
        schema = Schema(fields)
        self.output.emit(RecordBatch(schema, cols, end - 1))

    def _refresh_late(self) -> None:
        """Refresh the host cache of the device late-drop counter at
        fire/checkpoint boundaries — a /metrics scrape reads the cache
        alone and never forces a device sync mid-pipeline (the PR 8
        late_dropped lesson, applied to sessions too)."""
        # lint: sync-ok boundary-amortized refresh; scrapes read the cache
        self._late_cached = int(jax.device_get(self._late_dev))

    @property
    def late_dropped(self) -> int:
        return self._late_dropped + self._late_cached

    def finish(self) -> None:
        pass

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        self._refresh_late()
        return {"keyed": {
            "backend": self._backend.snapshot(checkpoint_id),
            "pending": [dict(c) for c in self._pending],
            "meta": {"fired_boundary": int(self._fired_boundary),
                     "watermark": self.current_watermark}}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        if keyed_snapshots:
            self._backend.restore(
                [s["backend"] for s in keyed_snapshots])
            # pending sessions re-filter by key group on rescale
            from ...core.keygroups import hash_batch, \
                key_groups_for_hash_batch
            for s in keyed_snapshots:
                for chunk in s.get("pending", []):
                    kg = key_groups_for_hash_batch(
                        hash_batch(chunk["k"]),
                        self._backend.max_parallelism)
                    mine = np.isin(
                        kg, np.arange(
                            self._backend.key_group_range.start,
                            self._backend.key_group_range.end + 1))
                    if mine.any():
                        self._pending.append(
                            {k: np.asarray(v)[mine]
                             for k, v in chunk.items()})
            self._fired_boundary = max(
                int(s["meta"]["fired_boundary"]) for s in keyed_snapshots)
            self.current_watermark = max(
                s["meta"]["watermark"] for s in keyed_snapshots)
            self._registered = False  # re-register agg planes lazily
