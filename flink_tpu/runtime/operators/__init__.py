from .base import (  # noqa: F401
    CollectingOutput, OneInputOperator, OperatorChain, OperatorContext,
    Output, StreamOperator, TwoInputOperator,
)
from .simple import (  # noqa: F401
    BatchFnOperator, FilterOperator, FlatMapOperator, KeyedProcessOperator,
    MapOperator,
)
from .sink import FunctionSinkOperator, SinkOperator  # noqa: F401
from .window import WindowOperator  # noqa: F401
