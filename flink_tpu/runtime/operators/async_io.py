"""Async I/O: non-blocking external lookups inside a stream.

Analog of the reference's AsyncWaitOperator + AsyncFunction (flink-streaming
api/operators/async/AsyncWaitOperator.java:92, AsyncDataStream): each record
issues an asynchronous request; up to ``capacity`` requests are in flight; a
full queue backpressures the task (the reference blocks the mailbox the same
way). Results re-enter the stream either in record order ("ordered") or as
they complete ("unordered"). Timeouts go through a retry policy, then either
fail the job or emit nothing ("ignore").

Batch-runtime adaptation: completed futures are drained at every batch /
watermark / processing-time tick instead of via mailbox mails. Checkpoints
snapshot the queue of un-resolved input elements (exactly the reference's
element-queue snapshot, AsyncWaitOperator.snapshotState) and re-submit them
on restore — results emitted after the barrier are covered by the snapshot,
so replay after failure reproduces them exactly once.
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Optional

from ...core.records import RecordBatch, Schema, scalar as _scalar
from .base import OneInputOperator

__all__ = ["AsyncFunction", "AsyncWaitOperator", "RetryPolicy"]


class AsyncFunction:
    """User hook (reference AsyncFunction): ``async_invoke`` returns the
    result rows directly (sync fast path) or a Future resolving to them.
    Result = one row tuple, an iterable of row tuples, or None (no
    output)."""

    def open(self) -> None:
        pass

    def async_invoke(self, row: tuple, timestamp: int):
        raise NotImplementedError

    def timeout(self, row: tuple):
        """Result to use when retries are exhausted in 'ignore' mode."""
        return None

    def close(self) -> None:
        pass


@dataclass
class RetryPolicy:
    """Fixed-delay retry (reference AsyncRetryStrategies)."""

    max_attempts: int = 3
    delay_ms: int = 100


@dataclass
class _Entry:
    row: tuple
    ts: int
    future: Any
    deadline: Optional[float]     # monotonic seconds
    attempts: int = 1
    not_before: float = 0.0       # retry backoff gate (monotonic)


class AsyncWaitOperator(OneInputOperator):
    DEFAULT_TIMEOUT_MS = 60_000  # the reference makes a timeout mandatory;
    # a hung request must never stall the pipeline forever

    def __init__(self, fn: AsyncFunction, capacity: int = 100,
                 timeout_ms: Optional[int] = None, mode: str = "ordered",
                 retry: Optional[RetryPolicy] = None,
                 on_timeout: str = "fail",
                 out_schema: Optional[Schema] = None,
                 name: str = "AsyncWait"):
        super().__init__(name)
        if mode not in ("ordered", "unordered"):
            raise ValueError("mode must be ordered|unordered")
        if on_timeout not in ("fail", "ignore"):
            raise ValueError("on_timeout must be fail|ignore")
        self._fn = fn
        self._capacity = capacity
        self._timeout_ms = (self.DEFAULT_TIMEOUT_MS if timeout_ms is None
                            else timeout_ms)
        self._mode = mode
        self._retry = retry or RetryPolicy(max_attempts=1)
        self._on_timeout = on_timeout
        self.out_schema = out_schema
        self._pending: deque[_Entry] = deque()
        self._restored_rows: list[tuple] = []  # (row, ts) from a snapshot

    # -- lifecycle ---------------------------------------------------------
    def open(self) -> None:
        self._fn.open()
        # re-submit requests that were in flight at the snapshot
        for row, ts in self._restored_rows:
            self._pending.append(self._submit(tuple(row), int(ts)))
        self._restored_rows = []

    def close(self) -> None:
        self._fn.close()

    # -- request plumbing --------------------------------------------------
    def _submit(self, row: tuple, ts: int, attempts: int = 1) -> _Entry:
        try:
            result = self._fn.async_invoke(row, ts)
        except Exception as exc:  # noqa: BLE001 - sync raise == failed future
            # a synchronous raise gets the same retry/ignore treatment as an
            # exceptionally-completed future
            result = Future()
            result.set_exception(exc)
        if not isinstance(result, Future):
            f: Future = Future()
            f.set_result(result)
            result = f
        deadline = (time.monotonic() + self._timeout_ms / 1000.0
                    if self._timeout_ms is not None else None)
        return _Entry(row, ts, result, deadline, attempts)

    def _fail_or_retry(self, e: _Entry, why: str) -> str:
        """Timeout or exceptional completion: schedule a retry (non-blocking
        backoff via not_before) or report terminal failure."""
        if e.attempts < self._retry.max_attempts:
            if e.future is not None:
                e.future.cancel()  # free queued work in the user's pool
            e.future = None  # resubmitted once the backoff gate opens
            e.not_before = time.monotonic() + self._retry.delay_ms / 1000.0
            return "waiting"
        return why

    def _entry_state(self, e: _Entry) -> str:
        """done | waiting | timed_out | failed."""
        now = time.monotonic()
        if e.future is None:  # waiting out a retry backoff
            if now < e.not_before:
                return "waiting"
            new = self._submit(e.row, e.ts, e.attempts + 1)
            e.future, e.deadline, e.attempts = \
                new.future, new.deadline, new.attempts
        if e.future.done():
            if e.future.exception() is not None:
                # exceptional completion retries like a timeout (reference
                # AsyncRetryStrategies retry on exceptions)
                return self._fail_or_retry(e, "failed")
            return "done"
        if e.deadline is not None and now > e.deadline:
            return self._fail_or_retry(e, "timed_out")
        return "waiting"

    def _resolve(self, e: _Entry, out_rows: list, out_ts: list,
                 state: str) -> None:
        if state in ("timed_out", "failed"):
            if self._on_timeout == "fail":
                if state == "failed":
                    raise e.future.exception()
                raise TimeoutError(
                    f"async request timed out after {e.attempts} attempts "
                    f"for row {e.row!r}")
            result = self._fn.timeout(e.row)
        else:
            result = e.future.result()
        if result is None:
            return
        rows = ([result] if isinstance(result, tuple)
                else list(result))
        for r in rows:
            out_rows.append(tuple(r) if not isinstance(r, tuple) else r)
            out_ts.append(e.ts)

    def _drain(self, wait_all: bool, out_rows: list, out_ts: list) -> None:
        """Pop completed entries. ordered: only from the head; unordered:
        anywhere. wait_all blocks until the queue is empty (barrier/finish/
        capacity)."""
        while self._pending:
            if self._mode == "ordered":
                head = self._pending[0]
                state = self._entry_state(head)
                if state == "waiting":
                    if not wait_all:
                        return
                    time.sleep(0.001)
                    continue
                self._pending.popleft()
                self._resolve(head, out_rows, out_ts, state)
            else:
                progressed = False
                for _ in range(len(self._pending)):
                    e = self._pending.popleft()
                    state = self._entry_state(e)
                    if state == "waiting":
                        self._pending.append(e)
                    else:
                        self._resolve(e, out_rows, out_ts, state)
                        progressed = True
                if not self._pending:
                    return
                if not wait_all:
                    return
                if not progressed:
                    time.sleep(0.001)

    def _emit(self, out_rows: list, out_ts: list) -> None:
        if not out_rows:
            return
        # from_rows_infer re-promotes per column even with a schema (the
        # MapOperator pattern), so later wider values never truncate
        batch, self.out_schema = RecordBatch.from_rows_infer(
            self.out_schema, out_rows, out_ts)
        self.output.emit(batch)

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        names = [f.name for f in batch.schema.fields]
        cols = [batch.column(n) for n in names]
        ts_arr = batch.timestamps
        out_rows: list = []
        out_ts: list = []
        for i in range(batch.n):
            row = tuple(_scalar(c[i]) for c in cols)
            while len(self._pending) >= self._capacity:
                # full queue = backpressure (reference blocks the mailbox)
                before = len(self._pending)
                self._drain(wait_all=False, out_rows=out_rows,
                            out_ts=out_ts)
                if len(self._pending) == before:
                    time.sleep(0.001)
            self._pending.append(self._submit(row, int(ts_arr[i])))
            self._drain(wait_all=False, out_rows=out_rows, out_ts=out_ts)
        self._emit(out_rows, out_ts)

    def process_watermark(self, watermark) -> None:
        # all requests for records before the watermark must resolve first
        out_rows: list = []
        out_ts: list = []
        self._drain(wait_all=True, out_rows=out_rows, out_ts=out_ts)
        self._emit(out_rows, out_ts)
        super().process_watermark(watermark)

    def advance_processing_time(self, now_ms: int) -> None:
        out_rows: list = []
        out_ts: list = []
        self._drain(wait_all=False, out_rows=out_rows, out_ts=out_ts)
        self._emit(out_rows, out_ts)

    def finish(self) -> None:
        out_rows: list = []
        out_ts: list = []
        self._drain(wait_all=True, out_rows=out_rows, out_ts=out_ts)
        self._emit(out_rows, out_ts)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        """Snapshot the queue of unresolved input elements (reference
        AsyncWaitOperator.snapshotState). The barrier has already been
        broadcast by the task, so results resolving later are emitted
        post-barrier — covered exactly by re-submitting these elements on
        restore (no drain here, which would leak post-barrier emissions
        out of checkpoint N)."""
        return {"operator": {
            "pending": [(list(e.row), e.ts) for e in self._pending]}}

    def initialize_state(self, keyed_snapshots: list,
                         operator_snapshot) -> None:
        if operator_snapshot and operator_snapshot.get("pending"):
            self._restored_rows = [(tuple(r), int(t))
                                   for r, t in operator_snapshot["pending"]]
