"""Generic keyed window operator (host row path).

Semantics follow the reference WindowOperator
(flink-streaming-java runtime/operators/windowing/WindowOperator.java:98 —
processElement:278, onEventTime:437, onProcessingTime:484,
emitWindowContents:552) including merging session windows (MergingWindowSet),
allowed lateness, late-data side output, and evictors
(EvictingWindowOperator). This operator is the correctness twin used for
parity tests and non-vectorizable windows (sessions, custom triggers); the
performance path is the device slice-window operator
(runtime/operators/device_window.py), whose outputs must match this one.

Window contents live in keyed state under namespace=window; cleanup timers
are (key, window.max_timestamp + allowed_lateness).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np

from ...core.elements import Watermark
from ...core.functions import AggregateFunction, ReduceAggregate, ReduceFunction
from ...core.records import MAX_TIMESTAMP, MIN_TIMESTAMP, RecordBatch, Schema
from ...state.descriptors import (
    AggregatingStateDescriptor, ListStateDescriptor, MapStateDescriptor,
)
from ...window.assigners import TimeWindow, WindowAssigner
from ...window.triggers import Evictor, Trigger, TriggerContext, TriggerResult
from ..timers import InternalTimerService, Timer
from .base import OneInputOperator, OperatorContext, Output
from .simple import KeyExtractor, _runtime_context

__all__ = ["WindowOperator", "WindowFunction", "LATE_DATA_TAG"]

LATE_DATA_TAG = "late-data"

# (key, window, result_or_elements) -> iterable of output rows
WindowFunction = Callable[[Any, Any, Any], Iterable[Any]]


def _default_window_fn(key, window, result):
    yield (key, result)


class _TriggerStateAccessor:
    def __init__(self, op: "WindowOperator", window):
        self._op, self._window = op, window

    def _map(self):
        self._op._backend.set_current_namespace(self._window)
        return self._op._backend.get_partitioned_state(self._op._trigger_desc)

    def get(self, name, default=None):
        v = self._map().get(name)
        return default if v is None else v

    def set(self, name, value):
        self._map().put(name, value)

    def clear(self, name):
        self._map().remove(name)


class WindowOperator(OneInputOperator):
    def __init__(self, assigner: WindowAssigner, key_extractor: KeyExtractor,
                 aggregate: Optional[AggregateFunction] = None,
                 reduce: Optional[ReduceFunction] = None,
                 window_fn: Optional[WindowFunction] = None,
                 trigger: Optional[Trigger] = None,
                 evictor: Optional[Evictor] = None,
                 allowed_lateness: int = 0,
                 emit_late_data: bool = False,
                 out_schema: Optional[Schema] = None,
                 name: str = "Window"):
        super().__init__(name)
        if aggregate is not None and reduce is not None:
            raise ValueError("Provide aggregate or reduce, not both")
        if reduce is not None:
            aggregate = ReduceAggregate(reduce)
        # evictor path keeps raw elements in list state (reference
        # EvictingWindowOperator); otherwise incremental aggregation
        self._evictor = evictor
        self._aggregate = aggregate
        self._assigner = assigner
        self._key_extractor = key_extractor
        self._window_fn = window_fn or _default_window_fn
        self._trigger = trigger or assigner.default_trigger()
        self._allowed_lateness = int(allowed_lateness)
        self._emit_late_data = emit_late_data
        self._out_schema = out_schema
        if assigner.is_merging and evictor is not None:
            raise ValueError("Evictors are not supported with merging windows")
        if assigner.is_merging and not self._trigger.can_merge():
            raise ValueError("Trigger cannot merge for merging window assigner")

        if self._evictor is not None or self._aggregate is None:
            self._contents_desc = ListStateDescriptor("window-contents")
        else:
            self._contents_desc = AggregatingStateDescriptor(
                "window-contents", self._aggregate)
        self._trigger_desc = MapStateDescriptor("window-trigger-state")
        self._merging_desc = MapStateDescriptor("merging-window-set")

        self._backend = None
        self._timers: Optional[InternalTimerService] = None
        self._pending_rows: list = []
        self._pending_ts: list[int] = []

    # -- lifecycle ---------------------------------------------------------
    def setup(self, ctx: OperatorContext, output: Output) -> None:
        super().setup(ctx, output)
        # per-(key, window) namespaced list/aggregating state: fall back to
        # the heap backend only when the CONFIGURED backend is a partial
        # one that cannot hold these shapes (tpu value plane) — a full
        # backend like changelog keeps its durability semantics
        from ...core.config import StateOptions
        from ...state.backend import backend_supports_general_state
        configured = ctx.config.get(StateOptions.BACKEND)
        self._backend = ctx.create_keyed_backend(
            name=None if backend_supports_general_state(configured)
            else "hashmap")
        self._timers = InternalTimerService(
            ctx.key_group_range, ctx.max_parallelism,
            on_event_time=self._on_event_time,
            on_processing_time=self._on_processing_time)

    def initialize_state(self, keyed_snapshots: list, operator_snapshot) -> None:
        if keyed_snapshots:
            self._backend.restore([s["backend"] for s in keyed_snapshots])
            self._timers.restore([s["timers"] for s in keyed_snapshots])

    def open(self) -> None:
        if self._aggregate is not None:
            self._aggregate.open(_runtime_context(self, self._backend))

    # -- state helpers -----------------------------------------------------
    def _contents(self, window):
        self._backend.set_current_namespace(window)
        return self._backend.get_partitioned_state(self._contents_desc)

    def _trigger_ctx(self, key, window) -> TriggerContext:
        return TriggerContext(key, window, self._timers,
                              _TriggerStateAccessor(self, window),
                              self.current_watermark)

    def _cleanup_time(self, window) -> int:
        if self._assigner.is_event_time:
            t = window.max_timestamp + self._allowed_lateness
            return t if t >= window.max_timestamp else MAX_TIMESTAMP
        return window.max_timestamp

    def _register_cleanup(self, key, window) -> None:
        """Cleanup timer uses namespace=window, same as trigger timers, so at
        allowed_lateness=0 the fire timer and the cleanup timer are ONE timer
        (exactly the reference's registerCleanupTimer behavior)."""
        t = self._cleanup_time(window)
        if t == MAX_TIMESTAMP:
            return
        if self._assigner.is_event_time:
            self._timers.register_event_time_timer(key, t, window)
        else:
            self._timers.register_processing_time_timer(key, t, window)

    def _is_window_late(self, window) -> bool:
        return (self._assigner.is_event_time and
                self._cleanup_time(window) <= self.current_watermark)

    # -- merging window set (reference MergingWindowSet) -------------------
    def _merge_set(self):
        self._backend.set_current_namespace(None)
        return self._backend.get_partitioned_state(self._merging_desc)

    def _add_merging_window(self, key, new_window: TimeWindow) -> Optional[TimeWindow]:
        """Insert new_window, merging any overlapping windows. Returns the
        actual (possibly merged) window, or None if the element is too late.
        Window contents stay under a stable 'state window' namespace; merges
        fold accumulators together."""
        mset = self._merge_set()
        mapping: dict = dict(mset.items())  # actual window -> state window
        overlapping = [w for w in mapping if w.intersects(new_window)]
        merged = new_window
        for w in overlapping:
            merged = merged.cover(w)

        if not overlapping:
            mapping[new_window] = new_window
            actual = new_window
        elif len(overlapping) == 1 and overlapping[0] == merged:
            actual = merged
        else:
            # merge state: fold all state windows into the first's. NOTE the
            # state handle is namespace-context-sensitive — switch the
            # backend's current namespace around every access.
            state_windows = [mapping[w] for w in overlapping]
            target_state = state_windows[0]
            handle = self._contents(target_state)
            for sw in state_windows[1:]:
                self._backend.set_current_namespace(sw)
                if self._contents_desc.kind == "aggregating":
                    acc = handle.get_accumulator()
                    self._backend.set_current_namespace(sw)
                    handle.clear()
                    if acc is not None:
                        self._backend.set_current_namespace(target_state)
                        handle.merge_accumulator(acc)
                else:
                    items = list(handle.get())
                    self._backend.set_current_namespace(sw)
                    handle.clear()
                    if items:
                        self._backend.set_current_namespace(target_state)
                        for it in items:
                            handle.add(it)
            for w in overlapping:
                ctx = self._trigger_ctx(key, w)
                self._trigger.clear(w, ctx)
                # the absorbed window's CLEANUP timer lives in the time
                # domain the assigner registered it in — deleting only the
                # event-time one would leave a stale processing-time timer
                # that later wipes the merged session's state
                if self._assigner.is_event_time:
                    self._timers.delete_event_time_timer(
                        key, self._cleanup_time(w), w)
                else:
                    self._timers.delete_processing_time_timer(
                        key, self._cleanup_time(w), w)
                del mapping[w]
            mapping[merged] = target_state
            self._trigger.on_merge(merged, self._trigger_ctx(key, merged))
            actual = merged

        new_map = self._merge_set()
        new_map.clear()
        for aw, sw in mapping.items():
            new_map.put(aw, sw)
        return actual

    def _state_window_for(self, actual_window):
        if not self._assigner.is_merging:
            return actual_window
        sw = self._merge_set().get(actual_window)
        return sw if sw is not None else actual_window

    # -- data path ---------------------------------------------------------
    def process_batch(self, batch: RecordBatch) -> None:
        if self._aggregate is not None and hasattr(self._aggregate, "bind_schema"):
            self._aggregate.bind_schema(batch.schema)
        keys = self._key_extractor(batch)
        if self._process_batch_grouped(batch, keys):
            return
        for i in range(batch.n):
            key = keys[i]
            key = key.item() if isinstance(key, np.generic) else key
            ts = int(batch.timestamps[i])
            if ts == MIN_TIMESTAMP and self._assigner.is_event_time:
                ts = self.current_watermark  # no timestamp: treat as on-time
            row = batch.row(i)
            self._backend.set_current_key(key)
            element_ts = ts if self._assigner.is_event_time \
                else self.ctx.processing_time()
            windows = self._assigner.assign_windows(element_ts)

            handled_any = False
            for window in windows:
                if self._assigner.is_merging:
                    window = self._add_merging_window(key, window)
                    if window is None:
                        continue
                if self._is_window_late(window):
                    continue
                handled_any = True
                state_window = self._state_window_for(window)
                contents = self._contents(state_window)
                if self._contents_desc.kind == "aggregating":
                    contents.add(row)
                else:
                    contents.add((row, ts))
                self._register_cleanup(key, window)
                result = self._trigger.on_element(
                    ts, window, self._trigger_ctx(key, window))
                self._handle_trigger_result(key, window, result)

            if not handled_any and self._assigner.is_event_time:
                if self._emit_late_data:
                    self.output.emit_side(
                        LATE_DATA_TAG,
                        RecordBatch.from_rows(batch.schema, [row], [ts]))
        self._flush_pending()

    def _process_batch_grouped(self, batch: RecordBatch, keys) -> bool:
        """Grouped fast path for the common window shape — non-merging
        event-time assigner, default EventTimeTrigger, incremental
        aggregation, no evictor, allowed_lateness 0: ONE state resolution
        and ONE timer registration per distinct (key, window) per batch
        instead of per record, with numpy partial folds for builtin
        aggregates (the host twin of the device operator's batch fold;
        reference shape: MiniBatch windowed aggregation). Returns False
        when the configuration needs the per-record path."""
        from ...window.assigners import (
            SlidingEventTimeWindows, TumblingEventTimeWindows,
        )
        from ...window.triggers import EventTimeTrigger

        a = self._assigner
        if (a.is_merging or self._evictor is not None
                or self._contents_desc.kind != "aggregating"
                or type(self._trigger) is not EventTimeTrigger
                or not a.is_event_time
                or self._allowed_lateness != 0
                or batch.n == 0):
            return False
        if isinstance(a, TumblingEventTimeWindows):
            size, slide, offset = a.size, a.size, a.offset
        elif isinstance(a, SlidingEventTimeWindows):
            size, slide, offset = a.size, a.slide, a.offset
            if size % slide != 0:
                return False
        else:
            return False
        ts = batch.timestamps
        if bool((ts == MIN_TIMESTAMP).any()):
            return False
        nwin = size // slide
        last_start = (ts - ((ts - offset) % slide)).astype(np.int64)
        wm = self.current_watermark
        # vectorizable builtin fold? (sum/min/max/count over one column)
        bk = getattr(self._aggregate, "builtin_kind", None)
        bf = getattr(self._aggregate, "builtin_field", None)
        col = None
        if bk in ("sum", "min", "max") or (bk == "count" and bf is None):
            if bk == "count":
                col = np.ones(batch.n, np.int64)
            elif isinstance(bf, str) and bf in batch.schema:
                col = np.asarray(batch.column(bf))
            elif isinstance(bf, int):
                col = np.asarray(
                    batch.columns[batch.schema.fields[bf].name])
            elif isinstance(bf, str) and len(batch.schema) == 1:
                col = np.asarray(batch.column(batch.schema.fields[0].name))
            if col is not None and col.dtype == object:
                col = None
        rows = None if col is not None else list(batch.iter_rows())
        # group (key, window_start) -> row indices; at lateness 0 the
        # EventTimeTrigger never fires on add (a passed window is late),
        # so grouping changes no observable behavior, only the number of
        # state/namespace round-trips
        groups: dict = {}
        newest_late = None
        for j in range(nwin):
            starts = last_start - j * slide
            late = starts + size - 1 <= wm
            if j == 0:
                newest_late = late
            for i in np.flatnonzero(~late):
                k = keys[i]
                k = k.item() if isinstance(k, np.generic) else k
                groups.setdefault((k, int(starts[i])), []).append(i)
        if self._emit_late_data and newest_late is not None \
                and newest_late.any():
            idx = np.flatnonzero(newest_late)
            self.output.emit_side(LATE_DATA_TAG, batch.take(idx))
        reducers = {"sum": np.sum, "min": np.min, "max": np.max,
                    "count": np.sum}
        reduce_fn = reducers[bk] if col is not None else None
        backend = self._backend
        # state handles read the backend's CURRENT key/namespace at access
        # time, so one handle serves every group (resolving it per group
        # was ~15% of this loop)
        backend.set_current_namespace(TimeWindow(0, size))
        contents = backend.get_partitioned_state(self._contents_desc)
        can_merge = hasattr(contents, "merge_accumulator")
        register = self._timers.register_event_time_timer
        for (key, start), idxs in groups.items():
            window = TimeWindow(start, start + size)
            backend.set_current_key(key)
            backend.set_current_namespace(window)
            if col is not None and can_merge:
                part = reduce_fn(col[idxs])
                contents.merge_accumulator(
                    part.item() if isinstance(part, np.generic) else part)
            else:
                for i in idxs:
                    contents.add(rows[i])
            # at allowed_lateness 0 the trigger's fire timer and the
            # cleanup timer are ONE timer at window.max_timestamp (the
            # per-record path documents the same collapse)
            register(key, window.max_timestamp, window)
        self._flush_pending()
        return True

    # -- firing ------------------------------------------------------------
    def _handle_trigger_result(self, key, window, result: TriggerResult) -> None:
        if result.fires:
            self._emit_window_contents(key, window)
        if result.purges:
            self._clear_window_contents(key, window)

    def _emit_window_contents(self, key, window) -> None:
        state_window = self._state_window_for(window)
        contents = self._contents(state_window)
        if self._contents_desc.kind == "aggregating":
            result = contents.get()
            if result is None:
                return
            out_rows = list(self._window_fn(key, window, result))
        else:
            elements = list(contents.get())
            if not elements:
                return
            if self._evictor is not None:
                elements = self._evictor.evict_before(
                    elements, window, self.current_watermark)
            if self._aggregate is not None:
                acc = self._aggregate.create_accumulator()
                for v, _ts in elements:
                    acc = self._aggregate.add(v, acc)
                payload = self._aggregate.get_result(acc)
            else:
                payload = [v for v, _ts in elements]
            out_rows = list(self._window_fn(key, window, payload))
            if self._evictor is not None:
                remaining = self._evictor.evict_after(
                    elements, window, self.current_watermark)
                contents.update(remaining)
        ts = window.max_timestamp if window.max_timestamp < MAX_TIMESTAMP \
            else self.current_watermark
        self._pending_rows.extend(out_rows)
        self._pending_ts.extend([ts] * len(out_rows))

    def _clear_window_contents(self, key, window) -> None:
        self._contents(self._state_window_for(window)).clear()

    def _clear_all_state(self, key, window) -> None:
        self._clear_window_contents(key, window)
        ctx = self._trigger_ctx(key, window)
        self._trigger.clear(window, ctx)
        self._backend.set_current_namespace(window)
        self._backend.get_partitioned_state(self._trigger_desc).clear()
        if self._assigner.is_merging:
            mset = self._merge_set()
            mset.remove(window)

    # -- timers ------------------------------------------------------------
    def _on_event_time(self, timer: Timer) -> None:
        self._fire_timer(timer, event_time=True)

    def _on_processing_time(self, timer: Timer) -> None:
        self._fire_timer(timer, event_time=False)

    def _fire_timer(self, timer: Timer, event_time: bool) -> None:
        key = timer.key
        window = timer.namespace
        if window is None:
            return
        self._backend.set_current_key(key)
        self._fire_via_trigger(key, window, timer.timestamp, event_time)
        # reference onEventTime/onProcessingTime: after the trigger runs, a
        # timer at cleanup time clears all window state
        if (event_time == self._assigner.is_event_time
                and timer.timestamp == self._cleanup_time(window)):
            self._clear_all_state(key, window)
        # no flush here: one watermark advance fires MANY timers (every
        # closed window of every key) and process_watermark flushes once
        # after the sweep — a per-timer flush built a one-row RecordBatch
        # per fired window

    def _fire_via_trigger(self, key, window, ts: int, event_time: bool) -> None:
        ctx = self._trigger_ctx(key, window)
        if event_time:
            result = self._trigger.on_event_time(ts, window, ctx)
        else:
            result = self._trigger.on_processing_time(ts, window, ctx)
        self._handle_trigger_result(key, window, result)

    def process_watermark(self, watermark: Watermark) -> None:
        self.current_watermark = watermark.timestamp
        self._timers.advance_watermark(watermark.timestamp)
        self._flush_pending()
        self.output.emit_watermark(watermark)

    def advance_processing_time(self, now_ms: int) -> None:
        self._timers.advance_processing_time(now_ms)
        self._flush_pending()

    def _flush_pending(self) -> None:
        if not self._pending_rows:
            return
        out, self._out_schema = RecordBatch.from_rows_infer(
            self._out_schema, self._pending_rows, self._pending_ts)
        self.output.emit(out)
        self._pending_rows, self._pending_ts = [], []

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self, checkpoint_id: int) -> dict:
        return {"keyed": {"backend": self._backend.snapshot(checkpoint_id),
                          "timers": self._timers.snapshot()}}
