"""Exact masked top-k by radix threshold selection.

``lax.top_k`` over a full [capacity] accumulator is the single most
expensive op in the device window-fire path (reference fire loop:
WindowOperator.onEventTime:437 emitting ORDER BY ... LIMIT k results) —
measured ~480 ms for k=1000 over 2M slots on one CPU host, because XLA
lowers it to a variant of full sort. The fire only needs the k largest
values and their slots, so this module finds the exact k-th threshold with
a fixed number of histogram passes (radix select) and then compacts the
survivors with one two-ended scatter:

* 4 passes of 16-bit histograms walk the 64-bit key space top-down; after
  pass p the threshold prefix is exact to 16*(p+1) bits, so 4 passes pin
  the exact k-th largest value T. Each pass is one elementwise extract +
  one scatter-add into 65536 bins — O(n) memory-bound work with no sort.
* survivors split into STRICT (> T, provably fewer than k) and TIES (== T,
  interchangeable by definition). Ties compact from the back of a [k]
  buffer, strict from the front, strict written last so collisions resolve
  in favor of strict — exactness without a second pass.

Values map monotonically into uint64 (sign-flip for signed ints, the
sign-magnitude trick for floats), so one implementation covers every
accumulator dtype. Invalid slots are excluded from both the histograms and
the final compaction.

Bounded non-negative integer domains (``value_bits <= 32`` — window
COUNTs, packed price words, everything the Q5 fire ranks on) take a
scatter-free bitwise-bisection path instead: the exact threshold is built
bit by bit with one vectorized compare-and-count per bit, and the winners
compact via cumsum + searchsorted. XLA lowers scatter to a serial loop,
so dropping the histogram scatter-adds and the two compaction scatters
makes the select several times faster at every size measured
(0.47 ms vs 3.6 ms at n=16k, 37 ms vs 188 ms at n=1M; k=1000).

Contract matches lax.top_k + validity: ``(values[k], indices[k], ok[k])``
sorted descending; ``ok[i]`` False marks padding when fewer than k valid
slots exist.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["masked_topk_radix", "masked_topk_sort", "masked_topk"]


def _to_uint64(v: jax.Array) -> jax.Array:
    """Monotone map of any ordered dtype into uint64 (order-preserving:
    a < b  <=>  map(a) < map(b))."""
    dt = v.dtype
    if jnp.issubdtype(dt, jnp.floating):
        bits = jax.lax.bitcast_convert_type(
            v, jnp.int32 if dt == jnp.float32 else jnp.int64)
        bits = bits.astype(jnp.int64)
        width = 32 if dt == jnp.float32 else 64
        sign = jnp.int64(1) << (width - 1)
        # positive floats: set sign bit; negative: flip all bits
        u = jnp.where(bits >= 0, bits | sign,
                      ~bits & ((sign << 1) - 1) if width == 32 else ~bits)
        u = u.astype(jnp.uint64)
        if width == 32:
            u = u << 32  # widen keeping order
        return u
    # signed ints: flip the sign bit after widening
    return (v.astype(jnp.int64).astype(jnp.uint64)
            ^ jnp.uint64(1) << jnp.uint64(63))


def masked_topk_radix(values: jax.Array, valid: jax.Array, k: int,
                      value_bits: int = 64):
    """Exact top-k among valid slots via 16-bit-per-pass radix select.

    ``value_bits``: static upper bound on the bit width of the value
    DOMAIN (after the monotone uint64 map the top bits are constant, so
    passes over them resolve nothing). 64 is always safe; callers that
    know their values are non-negative and bounded (window COUNTs, packed
    price words) pass a tighter bound to drop whole histogram passes —
    each pass is an O(n) scatter, the dominant cost at large n.
    """
    from .hash_table import ensure_x64

    ensure_x64()  # uint64 radix walk needs x64 enabled
    # tighter bound => non-negative values with the top bits constant
    # after the sign-flip map (1 at bit 63, 0 down to value_bits): seed
    # the prefix with those known bits and walk only the low fields.
    # Floats always take the full walk: their monotone map packs the
    # exponent into the HIGH bits, so a low-bits-only walk is wrong.
    if (value_bits >= 64
            or jnp.issubdtype(jnp.asarray(values).dtype, jnp.floating)):
        passes = 4
    else:
        passes = max(1, -(-value_bits // 16))
    if value_bits <= 32 and not jnp.issubdtype(jnp.asarray(values).dtype,
                                               jnp.floating):
        # non-negative integers below 2^32: bitwise threshold bisection —
        # value_bits compare-and-count passes plus a searchsorted
        # compaction, no scatter anywhere. XLA lowers scatter to a
        # serial per-element loop, so the histogram walk's 65536-bin
        # scatter-adds and the [n]->[k] compaction scatters dominate the
        # radix path end to end (measured 3.6 ms vs 0.47 ms at n=16k and
        # 188 ms vs 37 ms at n=1M for k=1000, value_bits=31 on one CPU
        # host); the bisection is pure vectorized compare/reduce/gather
        # and is also deterministic in its tie selection (index order),
        # identically on every backend.
        return _masked_topk_bisect(values, valid, k, value_bits)
    return _masked_topk_radix(values, valid, k, passes)


@partial(jax.jit, static_argnames=("k", "passes"))
def _masked_topk_radix(values: jax.Array, valid: jax.Array, k: int,
                       passes: int = 4):
    n = values.shape[0]
    k = min(k, n)
    u = _to_uint64(values)
    nvalid = jnp.sum(valid, dtype=jnp.int64)
    kk = jnp.minimum(jnp.int64(k), nvalid)          # effective k
    cand = valid
    above = jnp.int64(0)                             # strictly above prefix
    # with fewer than 4 passes the caller guarantees the skipped top bits
    # are constant (non-negative values below 2^(16*passes)): after the
    # sign flip that constant is exactly the sign bit
    prefix = jnp.uint64(0) if passes >= 4 else jnp.uint64(1) << 63
    bins = jnp.arange(65536, dtype=jnp.int64)
    for shift in (48, 32, 16, 0)[4 - passes:]:
        field = ((u >> shift) & jnp.uint64(0xFFFF)).astype(jnp.int32)
        hist = jnp.zeros(65536, jnp.int64).at[field].add(
            cand.astype(jnp.int64))
        # count of candidates at-or-above each bin (descending cumulative)
        revcum = jnp.cumsum(hist[::-1])[::-1]
        # above + revcum[0] >= kk always holds (revcum[0] counts every
        # candidate), so bstar is a real bin; when kk == 0 the condition
        # is all-True and bstar saturates at 65535 (downstream masks are
        # empty because valid is all-False in that case)
        cond = (above + revcum) >= kk
        bstar = jnp.max(jnp.where(cond, bins, -1))
        above = above + jnp.where(bins > bstar, hist, 0).sum()
        prefix = prefix | (bstar.astype(jnp.uint64) << shift)
        cand = cand & (field.astype(jnp.int64) == bstar)
    thr = prefix                                     # exact k-th largest
    strict = valid & (u > thr)                       # provably < kk of them
    tie = valid & (u == thr)
    # two independent 1-D scans (a stacked [2, n] cumsum hits a slow XLA
    # path: measured 72 ms vs 2x16 ms at n=2M on CPU)
    cum_s = jnp.cumsum(strict.astype(jnp.int64))
    cum_t = jnp.cumsum(tie.astype(jnp.int64))
    # strict compacts from the front, ties from the back; strict written
    # last so a collision keeps the strict element (ties all equal thr, so
    # dropping any particular tie is exact)
    tie_pos = jnp.clip(jnp.int64(k) - cum_t, 0, k - 1)
    strict_pos = cum_s - 1
    idx = jnp.arange(n, dtype=jnp.int64)
    # compact only the INDEX (2 scatter passes); values gather back from
    # the k winners — scatters over [n] are the cost that scales
    buf_i = jnp.full(k, -1, jnp.int64)
    buf_i = buf_i.at[jnp.where(tie, tie_pos, k)].set(idx, mode="drop")
    buf_i = buf_i.at[jnp.where(strict, strict_pos, k)].set(idx, mode="drop")
    filled = buf_i >= 0
    sent = _sentinel(values.dtype)
    buf_v = jnp.where(filled, values[jnp.maximum(buf_i, 0)], sent)
    # order filled-first then by value descending (filled slots with the
    # sentinel value are real data; unfilled sort behind via the flag)
    order = jnp.lexsort((jnp.where(filled, _to_uint64(buf_v),
                                   jnp.uint64(0)),
                         filled))[::-1]
    return buf_v[order], jnp.maximum(buf_i, 0)[order], filled[order]


@partial(jax.jit, static_argnames=("k", "bits"))
def _masked_topk_bisect(values: jax.Array, valid: jax.Array, k: int,
                        bits: int = 32):
    """Scatter-free exact top-k for non-negative integer domains below
    2^bits: find the exact k-th largest value T by building it bit by bit
    from the top — bit b joins the threshold iff at least kk candidates
    still sit at or above ``T | (1 << b)`` — then compact the winners
    with cumsum + searchsorted instead of scatters.

    Every pass is one vectorized compare + masked count over [n]; the
    compaction is two monotone-prefix binary searches of k targets. No
    scatter appears anywhere, which on CPU (where XLA lowers scatter to
    a serial loop) makes this several times faster than the histogram
    radix walk at every measured size, and the arithmetic is plain
    compare/reduce/gather that maps onto any backend identically.

    Tie handling is exact and deterministic: every slot strictly above T
    is included (provably fewer than kk of them), and remaining seats
    fill with the lowest-index slots equal to T — ties are
    interchangeable by definition, so this matches the radix contract."""
    n = values.shape[0]
    k = min(k, n)
    u = values.astype(jnp.uint32)
    nvalid = jnp.sum(valid, dtype=jnp.int32)
    kk = jnp.minimum(jnp.int32(k), nvalid)
    thr = jnp.uint32(0)
    for b in range(bits - 1, -1, -1):
        cand = thr | (jnp.uint32(1) << b)
        cnt = jnp.sum(valid & (u >= cand), dtype=jnp.int32)
        thr = jnp.where(cnt >= kk, cand, thr)
    strict = valid & (u > thr)
    tie = valid & (u == thr)
    cum_s = jnp.cumsum(strict.astype(jnp.int32))
    cum_t = jnp.cumsum(tie.astype(jnp.int32))
    n_s = cum_s[-1]
    # seat t (1-based): t-th strict slot while they last, then the
    # (t - n_s)-th tie slot; searchsorted on the monotone prefix sums
    # finds the index holding each rank without any scatter
    targets = jnp.arange(1, k + 1, dtype=jnp.int32)
    pos_s = jnp.searchsorted(cum_s, targets)
    pos_t = jnp.searchsorted(cum_t, jnp.maximum(targets - n_s, 1))
    idx = jnp.minimum(jnp.where(targets <= n_s, pos_s, pos_t), n - 1)
    filled = targets <= kk
    sent = _sentinel(values.dtype)
    buf_v = jnp.where(filled, values[idx], sent)
    order = jnp.lexsort((jnp.where(filled, buf_v.astype(jnp.uint32),
                                   jnp.uint32(0)),
                         filled))[::-1]
    return (buf_v[order], idx[order].astype(jnp.int64), filled[order])


def _sentinel(dtype):
    return (jnp.finfo(dtype).min if jnp.issubdtype(dtype, jnp.floating)
            else jnp.iinfo(dtype).min)


@partial(jax.jit, static_argnames=("k",))
def masked_topk_sort(values: jax.Array, valid: jax.Array, k: int):
    """lax.top_k reference implementation (XLA sort-based)."""
    sent = _sentinel(values.dtype)
    masked = jnp.where(valid, values, sent)
    kk = min(k, values.shape[0])
    vals, idx = jax.lax.top_k(masked, kk)
    return vals, idx, jnp.take(valid, idx)


def masked_topk(values: jax.Array, valid: jax.Array, k: int,
                value_bits: int = 64):
    """Backend-tuned exact masked top-k: radix select everywhere by
    default (XLA's sort-based top_k measured ~7x slower at [2M], k=1000 on
    CPU; radix is O(n) scatter/reduce passes that also map well onto TPU
    HBM bandwidth). Consumers needing the sort-based lowering can call
    masked_topk_sort directly."""
    return masked_topk_radix(values, valid, k, value_bits)
