"""Segment-reduce kernels: the device aggregation primitives.

These are the lowering targets for the framework's aggregate contract
(core AggregateFunction add/merge — reference AggregateFunction.java:114) and
for the window/group aggregations (reference WindowOperator + table-runtime
GroupAggFunction): each micro-batch folds into per-(pane, slot) accumulators
with ONE scatter op per aggregate, and window fire merges pane accumulators
with one reduction — no per-record work anywhere.

All functions are jax-traceable and shard_map-compatible (accumulators are
per-shard; cross-shard merge is the caller's psum/all_gather).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["scatter_fold", "pane_window_merge", "AGG_INITS", "AGG_FOLDS",
           "AGG_MERGES", "AGG_COMBINE2", "AGG_INVERT", "INVERTIBLE_KINDS",
           "make_accumulator", "segment_topk", "pow2_ceil",
           "merge_tree_build", "merge_tree_update", "merge_tree_root"]


def _scatter_add(acc, idx, vals):
    return acc.at[idx].add(vals)


def _scatter_min(acc, idx, vals):
    return acc.at[idx].min(vals)


def _scatter_max(acc, idx, vals):
    return acc.at[idx].max(vals)


#: kind -> (identity element factory, scatter fold, pane merge)
AGG_INITS = {
    "sum": lambda dtype: jnp.array(0, dtype),
    "count": lambda dtype: jnp.array(0, dtype),
    "min": lambda dtype: jnp.array(jnp.finfo(dtype).max
                                   if jnp.issubdtype(dtype, jnp.floating)
                                   else jnp.iinfo(dtype).max, dtype),
    "max": lambda dtype: jnp.array(jnp.finfo(dtype).min
                                   if jnp.issubdtype(dtype, jnp.floating)
                                   else jnp.iinfo(dtype).min, dtype),
}

AGG_FOLDS = {
    "sum": _scatter_add,
    "count": _scatter_add,
    "min": _scatter_min,
    "max": _scatter_max,
}

#: kind -> pane-merge reduction (callable(x, axis=...))
AGG_MERGES = {
    "sum": jnp.sum,
    "count": jnp.sum,
    "min": lambda x, axis: jnp.min(x, axis=axis),
    "max": lambda x, axis: jnp.max(x, axis=axis),
}
_MERGES = AGG_MERGES

#: kind -> elementwise pairwise combine (a ⊕ b) — the binary form of the
#: pane merge, used by the incremental fire engine (running window
#: accumulators and merge-tree levels).
AGG_COMBINE2 = {
    "sum": jnp.add,
    "count": jnp.add,
    "min": jnp.minimum,
    "max": jnp.maximum,
}

#: kind -> inverse combine (a ⊖ b), defined only for invertible aggregates.
AGG_INVERT = {
    "sum": jnp.subtract,
    "count": jnp.subtract,
}

#: Aggregate kinds whose combine has an inverse: a running window
#: accumulator can retire a pane by subtraction. min/max are not in this
#: set and use a merge tree instead.
INVERTIBLE_KINDS = frozenset(AGG_INVERT)


def make_accumulator(kind: str, shape: tuple[int, ...], dtype) -> jax.Array:
    return jnp.full(shape, AGG_INITS[kind](dtype), dtype=dtype)


def scatter_fold(kind: str, acc: jax.Array, flat_idx: jax.Array,
                 values: jax.Array, valid: jax.Array) -> jax.Array:
    """Fold a batch into a flat accumulator: acc[flat_idx] op= values,
    masked by ``valid`` (invalid rows fold the identity into slot 0)."""
    identity = AGG_INITS[kind](acc.dtype)
    idx = jnp.where(valid, flat_idx, 0)
    vals = jnp.where(valid, values.astype(acc.dtype), identity)
    return AGG_FOLDS[kind](acc, idx, vals)


def pane_window_merge(kind: str, acc: jax.Array,
                      pane_rows: jax.Array) -> jax.Array:
    """Merge selected pane rows of a [ring, capacity] accumulator into one
    [capacity] result — the slice-shared window fire
    (reference SliceSharedWindowAggProcessor)."""
    return _MERGES[kind](acc[pane_rows], 0)


@partial(jax.jit, static_argnames=("k",))
def segment_topk(values: jax.Array, valid: jax.Array, k: int
                 ) -> tuple[jax.Array, jax.Array]:
    """Top-k over a slot-indexed value array (Nexmark Q5 'hot items'):
    returns (topk values, topk slot indices)."""
    neg_inf = (jnp.finfo(values.dtype).min
               if jnp.issubdtype(values.dtype, jnp.floating)
               else jnp.iinfo(values.dtype).min)
    masked = jnp.where(valid, values, neg_inf)
    return jax.lax.top_k(masked, k)


def merge_tree_build(kind: str, leaves: jax.Array) -> jax.Array:
    """Build a flat binary merge tree over ``leaves`` [L, capacity] (L a
    power of two). Returns a heap-ordered [2L, capacity] array: node 0 is
    the identity (padding target), node 1 the root, children of node i at
    2i and 2i+1, leaves occupying rows [L, 2L). A window fire reads the
    root; a pane seal rewrites one leaf and its log2(L) ancestors."""
    L = leaves.shape[0]
    combine = AGG_COMBINE2[kind]
    levels = [leaves]
    cur = leaves
    while cur.shape[0] > 1:
        cur = combine(cur[0::2], cur[1::2])
        levels.append(cur)
    ident = jnp.full(leaves.shape[1:], AGG_INITS[kind](leaves.dtype),
                     leaves.dtype)
    return jnp.concatenate([ident[None]] + list(reversed(levels)), axis=0)


def merge_tree_update(kind: str, tree: jax.Array, leaf_pos: jax.Array,
                      value: jax.Array) -> jax.Array:
    """Functionally set leaf ``leaf_pos`` (traced int in [0, L)) of a heap
    tree [2L, capacity] to ``value`` [capacity] and recompute its ancestor
    path — O(log L) dynamic row updates, shape-independent of which leaf
    changed."""
    L = tree.shape[0] // 2
    combine = AGG_COMBINE2[kind]
    idx = (leaf_pos + L).astype(jnp.int32)
    tree = jax.lax.dynamic_update_slice_in_dim(tree, value[None], idx, axis=0)
    for _ in range(max(L.bit_length() - 1, 0)):
        idx = idx // 2
        parent = combine(tree[2 * idx], tree[2 * idx + 1])
        tree = jax.lax.dynamic_update_slice_in_dim(tree, parent[None], idx,
                                                   axis=0)
    return tree


def merge_tree_root(tree: jax.Array) -> jax.Array:
    """The full-tree merge: root of a heap-ordered merge tree."""
    return tree[1]


def pow2_ceil(n: int) -> int:
    """Next power of two >= n (n >= 1). Batches pad to power-of-two
    lengths so one compiled executable serves every upstream batch size —
    variable lengths (e.g. behind a WHERE filter) otherwise force an XLA
    recompile per distinct shape (measured 15x slower than the fold
    itself on the device GROUP BY path)."""
    return 1 << (n - 1).bit_length() if n > 1 else 1
