"""Pallas TPU kernel for the top-k radix-select histogram (A/B vs XLA).

The fire-path top-k (ops/topk.py) is O(n) histogram passes; under XLA
each pass lowers to a scatter-add — correct, but scatter is the op XLA
lowers most conservatively on TPU. This module implements the same
histogram as a Pallas kernel using the TPU-native formulation: per-block
ONE-HOT expansion + reduction (compare-and-sum runs on the VPU/MXU at
full vector width; no scatter at all), accumulated across grid steps in
VMEM.

The kernel uses 8-bit digits (256 bins) so the one-hot block stays small
in VMEM ([block, 256] int32 = 2 MB at block 2048); a 32-bit walk is <= 4
passes instead of the XLA path's <= 2 passes of 16-bit digits — the A/B
(bench.py: topk_ab_* metrics) decides which wins on real hardware, per
VERDICT r4 #7: measure, keep the winner, record the number.

``masked_topk_pallas`` matches ``ops.topk.masked_topk``'s contract for
non-negative integer domains below 2^32 (the count/packed-word fires);
other dtypes fall back to the XLA path. ``interpret=True`` runs the
kernel in the Pallas interpreter for CPU correctness tests.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics.device import instrumented_program_cache

__all__ = ["histogram256_pallas", "masked_topk_pallas",
           "pallas_available"]

_BLOCK = 2048


def _hist_kernel(u_ref, valid_ref, out_ref, *, shift: int):
    """One grid step: 256-bin histogram of ((u >> shift) & 0xFF) over a
    [BLOCK] slice, masked by ``valid``, accumulated into out_ref[8, 256]
    (rows summed by the caller; 8 rows keep the int32 tile shape)."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    u = u_ref[:]                                       # [BLOCK] int32
    ids = jax.lax.shift_right_logical(
        u, jnp.int32(shift)) & jnp.int32(0xFF)
    ids3 = ids.reshape(_BLOCK // 8, 8, 1)
    bins = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 256), 2)
    onehot = (ids3 == bins).astype(jnp.int32)          # [B/8, 8, 256]
    mask = valid_ref[:].reshape(_BLOCK // 8, 8, 1).astype(jnp.int32)
    out_ref[:, :] += (onehot * mask).sum(axis=0)


@partial(jax.jit, static_argnames=("shift", "interpret"))
def histogram256_pallas(u: jax.Array, valid: jax.Array, shift: int,
                        interpret: bool = False) -> jax.Array:
    """[256] int32 histogram of ((u >> shift) & 0xFF) where valid."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = u.shape[0]
    P = ((n + _BLOCK - 1) // _BLOCK) * _BLOCK
    if P != n:
        u = jnp.concatenate([u, jnp.zeros(P - n, u.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros(P - n, valid.dtype)])
    grid = (P // _BLOCK,)
    out = pl.pallas_call(
        partial(_hist_kernel, shift=shift),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK,), lambda i: (i,)),
            pl.BlockSpec((_BLOCK,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((8, 256), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 256), jnp.int32),
        interpret=interpret,
    )(u.astype(jnp.int32), valid.astype(jnp.int32))
    return out.sum(axis=0)


def masked_topk_pallas(values: jax.Array, valid: jax.Array, k: int,
                       value_bits: int = 32, interpret: bool = False):
    """Exact masked top-k via Pallas histogram radix select (8-bit
    digits). Contract identical to ops.topk.masked_topk for non-negative
    integer domains < 2^32; other inputs take the XLA path."""
    from .topk import masked_topk

    if (value_bits > 32
            or jnp.issubdtype(jnp.asarray(values).dtype, jnp.floating)):
        return masked_topk(values, valid, k, value_bits)
    passes = max(1, -(-value_bits // 8))
    from ..runtime.watchdog import stall_bounded
    return stall_bounded(
        "device.execute",
        lambda: _topk_program(int(k), int(passes),
                              bool(interpret))(values, valid),
        scope="pallas_topk")


@instrumented_program_cache("ops.pallas_topk", maxsize=32)
def _topk_program(k: int, passes: int, interpret: bool):
    """One jitted program per (k, passes, interpret); shapes re-trace
    inside jax.jit as usual, the builder cache is what the compile
    accounting watches."""

    @jax.jit
    def run(values, valid):
        return _topk_pallas(values, valid, k, passes, interpret)

    return run


def _topk_pallas(values, valid, k, passes, interpret):
    n = values.shape[0]
    k = min(k, n)
    u = values.astype(jnp.uint32)
    nvalid = jnp.sum(valid, dtype=jnp.int32)
    kk = jnp.minimum(jnp.int32(k), nvalid)
    cand = valid
    above = jnp.int32(0)
    prefix = jnp.uint32(0)
    bins = jnp.arange(256, dtype=jnp.int32)
    for shift in (24, 16, 8, 0)[4 - passes:]:
        hist = histogram256_pallas(u.view(jnp.int32)
                                   if u.dtype == jnp.uint32 else u,
                                   cand, shift, interpret=interpret)
        revcum = jnp.cumsum(hist[::-1])[::-1]
        cond = (above + revcum) >= kk
        bstar = jnp.max(jnp.where(cond, bins, -1))
        above = above + jnp.where(bins > bstar, hist, 0).sum()
        prefix = prefix | (bstar.astype(jnp.uint32) << shift)
        field = ((u >> shift) & jnp.uint32(0xFF)).astype(jnp.int32)
        cand = cand & (field == bstar)
    thr = prefix
    strict = valid & (u > thr)
    tie = valid & (u == thr)
    cum_s = jnp.cumsum(strict.astype(jnp.int32))
    cum_t = jnp.cumsum(tie.astype(jnp.int32))
    tie_pos = jnp.clip(jnp.int32(k) - cum_t, 0, k - 1)
    strict_pos = cum_s - 1
    idx = jnp.arange(n, dtype=jnp.int32)
    buf_i = jnp.full(k, -1, jnp.int32)
    buf_i = buf_i.at[jnp.where(tie, tie_pos, k)].set(idx, mode="drop")
    buf_i = buf_i.at[jnp.where(strict, strict_pos, k)].set(idx, mode="drop")
    filled = buf_i >= 0
    sent = jnp.iinfo(values.dtype).min
    buf_v = jnp.where(filled, values[jnp.maximum(buf_i, 0)], sent)
    order = jnp.lexsort((jnp.where(filled, buf_v.astype(jnp.uint32),
                                   jnp.uint32(0)), filled))[::-1]
    return (buf_v[order], jnp.maximum(buf_i, 0)[order].astype(jnp.int64),
            filled[order])


def _probe() -> bool:
    """Can a trivial Pallas kernel compile on this backend?"""
    try:
        if jax.default_backend() != "tpu":
            return False
        histogram256_pallas(jnp.zeros(256, jnp.int32),
                            jnp.ones(256, jnp.int32), 0)
        return True
    except Exception:  # noqa: BLE001 - absence of pallas support
        return False


@functools.lru_cache(maxsize=1)
def pallas_available() -> bool:
    return _probe()
