"""Device-resident open-addressing hash table: int64 key -> dense slot.

The core of the TPU keyed-state backend (SURVEY.md §7 step 3, the
FRocksDB-replacement): keyed state lives in dense device arrays indexed by
slot; this table maps unbounded keys onto those static-shape arrays entirely
on device, so the per-batch hot path never touches the host.

Algorithm: linear probing over a power-of-two table with a vectorized
parallel insert. Each iteration, every unresolved record reads its probe
slot; records that see EMPTY race to claim it with a single ``scatter-min``
(deterministic winner = smallest key); records that see a foreign key advance
their probe. Claims only target slots read as EMPTY in the same iteration, so
occupied slots are never corrupted; duplicate keys follow identical probe
sequences and resolve to the same slot. Bounded probe count returns an ``ok``
mask instead of looping forever (host rehashes on overflow).

Keys are int64 with EMPTY = int64 max as the sentinel (a real key equal to
the sentinel is remapped by the caller — see state/tpu_backend.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_X64_READY = False


def ensure_x64() -> None:
    """Keyed state uses full 64-bit keys on device (XLA emulates i64 on TPU
    with i32 pairs — fine for the compare/scatter ops the table needs).
    Flipped at first *use* of the device state path, not at import, so merely
    importing the library never changes a user program's default dtypes."""
    global _X64_READY
    if not _X64_READY:
        jax.config.update("jax_enable_x64", True)
        _X64_READY = True

__all__ = ["EMPTY_KEY", "make_table", "lookup", "lookup_or_insert",
           "hash_keys_device", "sanitize_keys_device", "ensure_x64",
           "MAX_PROBES"]

EMPTY_KEY = np.int64(np.iinfo(np.int64).max)
MAX_PROBES = 128


def sanitize_keys_device(keys: jax.Array) -> jax.Array:
    """Remap the EMPTY sentinel (int64 max) to int64 max - 1 — THE sentinel
    rule, shared by every device ingest path (host twin:
    state/tpu_backend._sanitize_keys)."""
    keys = keys.astype(jnp.int64)
    return jnp.where(keys == jnp.int64(EMPTY_KEY), jnp.int64(EMPTY_KEY) - 1,
                     keys)


def make_table(capacity: int) -> jax.Array:
    """capacity must be a power of two."""
    ensure_x64()
    if capacity & (capacity - 1):
        raise ValueError(f"capacity {capacity} not a power of two")
    return jnp.full((capacity,), EMPTY_KEY, dtype=jnp.int64)


def hash_keys_device(keys: jax.Array) -> jax.Array:
    """Murmur-style finalizer over int64 keys -> uint32 hash, matching the
    host path's spread (keygroups.murmur_mix over Long.hashCode-folded keys)
    closely enough for probing (exact parity is only required for key-group
    routing, which happens before this table)."""
    u = keys.astype(jnp.uint64)
    h = (u ^ (u >> 32)).astype(jnp.uint32)
    h = h * jnp.uint32(0xCC9E2D51)
    h = (h << 15) | (h >> 17)
    h = h * jnp.uint32(0x1B873593)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    return h


@jax.jit
def lookup(table_keys: jax.Array, keys: jax.Array) -> jax.Array:
    """Find slots for keys; -1 where absent. Vectorized bounded probing."""
    cap = table_keys.shape[0]
    mask = jnp.uint32(cap - 1)
    h0 = hash_keys_device(keys) & mask

    def body(state):
        probe, slot, done = state
        idx = (h0 + probe) & mask
        entry = table_keys[idx.astype(jnp.int32)]
        found = entry == keys
        empty = entry == EMPTY_KEY
        slot = jnp.where(~done & found, idx.astype(jnp.int32), slot)
        done = done | found | empty  # empty => key absent
        probe = jnp.where(done, probe, probe + 1)
        return probe, slot, done

    def cond(state):
        probe, _slot, done = state
        return ((~done) & (probe < MAX_PROBES)).any()

    n = keys.shape[0]
    init = (jnp.zeros(n, jnp.uint32), jnp.full(n, -1, jnp.int32),
            jnp.zeros(n, bool))
    _, slot, _ = jax.lax.while_loop(cond, body, init)
    return slot


@jax.jit
def lookup_or_insert(table_keys: jax.Array, keys: jax.Array,
                     valid: jax.Array | None = None
                     ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Find-or-claim slots for a batch of keys.

    Returns (new_table_keys, slots int32, ok bool). Records that exhaust
    MAX_PROBES report ok=False with slot=-1 (host should rehash bigger).
    Rows where ``valid`` is False never probe or claim (slot=-1, ok=False) —
    the sharded exchange feeds padded batches through here.
    """
    cap = table_keys.shape[0]
    mask = jnp.uint32(cap - 1)
    h0 = hash_keys_device(keys) & mask
    n = keys.shape[0]

    def body(state):
        table, probe, slot, done = state
        idx = ((h0 + probe) & mask).astype(jnp.int32)
        entry = table[idx]
        found = entry == keys
        empty = entry == EMPTY_KEY
        # claim: losers of the scatter-min re-read next iteration
        claim_idx = jnp.where(~done & empty, idx, jnp.int32(0))
        claim_val = jnp.where(~done & empty, keys, EMPTY_KEY)
        table = table.at[claim_idx].min(claim_val)
        entry2 = table[idx]
        won = ~done & empty & (entry2 == keys)
        slot = jnp.where(~done & (found | won), idx, slot)
        done = done | found | won
        probe = jnp.where(done, probe, probe + 1)
        return table, probe, slot, done

    def cond(state):
        _table, probe, _slot, done = state
        return ((~done) & (probe < MAX_PROBES)).any()

    start_done = (jnp.zeros(n, bool) if valid is None
                  else ~valid.astype(bool))
    init = (table_keys, jnp.zeros(n, jnp.uint32),
            jnp.full(n, -1, jnp.int32), start_done)
    table, _probe, slot, done = jax.lax.while_loop(cond, body, init)
    return table, slot, done & (slot >= 0)
