"""Device-resident open-addressing hash table: int64 key -> dense slot.

The core of the TPU keyed-state backend (SURVEY.md §7 step 3, the
FRocksDB-replacement): keyed state lives in dense device arrays indexed by
slot; this table maps unbounded keys onto those static-shape arrays entirely
on device, so the per-batch hot path never touches the host.

Algorithm: linear probing over a power-of-two table with a vectorized
parallel insert, probing in CHUNK-slot windows. Each iteration, every
unresolved record gathers its next CHUNK consecutive probe slots in one
[B, CHUNK] read (consecutive slots share cache lines / vector lanes, so a
window costs little more than a single slot — measured 2.3x over one-slot
probing at 50% load on CPU) and resolves the window at once: the first
match wins; otherwise records that see EMPTY race to claim the window's
FIRST empty slot with a single ``scatter-min`` (deterministic winner =
smallest key); losers resume from the contested slot. Claims only target
slots read as EMPTY in the same iteration, so occupied slots are never
corrupted; duplicate keys follow identical probe sequences and claim the
same first-empty slot (the loser sees its own key and resolves). The
insert-only invariant (empties never reappear) guarantees a present key
can never sit behind an empty slot in its probe sequence, so
first-match-before-first-empty decides containment. Bounded probe count
returns an ``ok`` mask instead of looping forever (host rehashes on
overflow).

Keys are int64 with EMPTY = int64 max as the sentinel (a real key equal to
the sentinel is remapped by the caller — see state/tpu_backend.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_X64_READY = False


def ensure_x64() -> None:
    """Keyed state uses full 64-bit keys on device (XLA emulates i64 on TPU
    with i32 pairs — fine for the compare/scatter ops the table needs).
    Flipped at first *use* of the device state path, not at import, so merely
    importing the library never changes a user program's default dtypes."""
    global _X64_READY
    if not _X64_READY:
        jax.config.update("jax_enable_x64", True)
        _X64_READY = True

__all__ = ["EMPTY_KEY", "make_table", "lookup", "lookup_or_insert",
           "hash_keys_device", "sanitize_keys_device", "ensure_x64",
           "MAX_PROBES"]

EMPTY_KEY = np.int64(np.iinfo(np.int64).max)
MAX_PROBES = 128
CHUNK = 8  # probe-window width: one 64-byte cache line of int64 slots


def sanitize_keys_device(keys: jax.Array) -> jax.Array:
    """Remap the EMPTY sentinel (int64 max) to int64 max - 1 — THE sentinel
    rule, shared by every device ingest path (host twin:
    state/tpu_backend._sanitize_keys)."""
    keys = keys.astype(jnp.int64)
    return jnp.where(keys == jnp.int64(EMPTY_KEY), jnp.int64(EMPTY_KEY) - 1,
                     keys)


def make_table(capacity: int) -> jax.Array:
    """capacity must be a power of two."""
    ensure_x64()
    if capacity & (capacity - 1):
        raise ValueError(f"capacity {capacity} not a power of two")
    return jnp.full((capacity,), EMPTY_KEY, dtype=jnp.int64)


def hash_keys_device(keys: jax.Array) -> jax.Array:
    """Murmur-style finalizer over int64 keys -> uint32 hash, matching the
    host path's spread (keygroups.murmur_mix over Long.hashCode-folded keys)
    closely enough for probing (exact parity is only required for key-group
    routing, which happens before this table)."""
    u = keys.astype(jnp.uint64)
    h = (u ^ (u >> 32)).astype(jnp.uint32)
    h = h * jnp.uint32(0xCC9E2D51)
    h = (h << 15) | (h >> 17)
    h = h * jnp.uint32(0x1B873593)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    return h


@jax.jit
def lookup(table_keys: jax.Array, keys: jax.Array) -> jax.Array:
    """Find slots for keys; -1 where absent. Vectorized bounded probing in
    CHUNK-slot windows (first empty before first match => absent)."""
    cap = table_keys.shape[0]
    mask = jnp.uint32(cap - 1)
    h0 = hash_keys_device(keys) & mask
    n = keys.shape[0]
    offs = jnp.arange(CHUNK, dtype=jnp.uint32)
    rng = jnp.arange(CHUNK, dtype=jnp.int32)
    C = jnp.int32(CHUNK)

    def body(state):
        base, slot, done = state
        idx = (((h0 + base)[:, None] + offs[None, :]) & mask).astype(
            jnp.int32)
        entry = table_keys[idx]                              # [n, CHUNK]
        is_key = entry == keys[:, None]
        is_empty = entry == jnp.int64(EMPTY_KEY)
        pos_found = jnp.min(jnp.where(is_key, rng[None], C), axis=1)
        pos_empty = jnp.min(jnp.where(is_empty, rng[None], C), axis=1)
        found = (~done) & (pos_found < pos_empty)
        fslot = jnp.take_along_axis(
            idx, jnp.minimum(pos_found, C - 1)[:, None], axis=1)[:, 0]
        slot = jnp.where(found, fslot, slot)
        done = done | found | (pos_empty < C)  # empty first => absent
        base = jnp.where(done, base, base + jnp.uint32(CHUNK))
        return base, slot, done

    def cond(state):
        base, _slot, done = state
        return ((~done) & (base < MAX_PROBES)).any()

    init = (jnp.zeros(n, jnp.uint32), jnp.full(n, -1, jnp.int32),
            jnp.zeros(n, bool))
    _, slot, _ = jax.lax.while_loop(cond, body, init)
    return slot


@jax.jit
def lookup_or_insert(table_keys: jax.Array, keys: jax.Array,
                     valid: jax.Array | None = None
                     ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Find-or-claim slots for a batch of keys.

    Returns (new_table_keys, slots int32, ok bool). Records that exhaust
    MAX_PROBES report ok=False with slot=-1 (host should rehash bigger).
    Rows where ``valid`` is False never probe or claim (slot=-1, ok=False) —
    the sharded exchange feeds padded batches through here.
    """
    cap = table_keys.shape[0]
    mask = jnp.uint32(cap - 1)
    h0 = hash_keys_device(keys) & mask
    n = keys.shape[0]
    offs = jnp.arange(CHUNK, dtype=jnp.uint32)
    rng = jnp.arange(CHUNK, dtype=jnp.int32)
    C = jnp.int32(CHUNK)

    def body(state):
        table, base, slot, done = state
        idx = (((h0 + base)[:, None] + offs[None, :]) & mask).astype(
            jnp.int32)
        entry = table[idx]                                   # [n, CHUNK]
        is_key = entry == keys[:, None]
        is_empty = entry == jnp.int64(EMPTY_KEY)
        pos_found = jnp.min(jnp.where(is_key, rng[None], C), axis=1)
        pos_empty = jnp.min(jnp.where(is_empty, rng[None], C), axis=1)
        found = (~done) & (pos_found < pos_empty)
        fslot = jnp.take_along_axis(
            idx, jnp.minimum(pos_found, C - 1)[:, None], axis=1)[:, 0]
        # claim the window's first empty; losers of the scatter-min resume
        # from the contested slot next iteration
        want = (~done) & ~found & (pos_empty < C)
        cslot = jnp.take_along_axis(
            idx, jnp.minimum(pos_empty, C - 1)[:, None], axis=1)[:, 0]
        claim_idx = jnp.where(want, cslot, jnp.int32(0))
        claim_val = jnp.where(want, keys, jnp.int64(EMPTY_KEY))
        table = table.at[claim_idx].min(claim_val)
        entry2 = table[cslot]
        won = want & (entry2 == keys)
        slot = jnp.where(found, fslot, slot)
        slot = jnp.where(won, cslot, slot)
        done = done | found | won
        base = jnp.where(
            done, base,
            base + jnp.where(want, pos_empty.astype(jnp.uint32),
                             jnp.uint32(CHUNK)))
        return table, base, slot, done

    def cond(state):
        _table, base, _slot, done = state
        return ((~done) & (base < MAX_PROBES)).any()

    start_done = (jnp.zeros(n, bool) if valid is None
                  else ~valid.astype(bool))
    init = (table_keys, jnp.zeros(n, jnp.uint32),
            jnp.full(n, -1, jnp.int32), start_done)
    table, _base, slot, done = jax.lax.while_loop(cond, body, init)
    return table, slot, done & (slot >= 0)
