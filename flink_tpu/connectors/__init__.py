"""Connectors: split-based sources and two-phase sinks (reference
flink-connectors + api/connector SPI). See core.py (SPI + collection/datagen),
file.py (FileSource/FileSink), socket.py, log.py (Kafka-shaped)."""

from .core import (
    CollectionSource, CollectSink, DataGenSource, PrintSink, Sink,
    SinkWriter, Source, SourceReader, SourceSplit,
)
from .file import FileSink, FileSource
from .log import InMemoryLogBroker, LogBroker, LogSink, LogSource
from .socket import SocketSource

__all__ = [
    "Source", "SourceReader", "SourceSplit", "Sink", "SinkWriter",
    "CollectionSource", "DataGenSource", "CollectSink", "PrintSink",
    "FileSource", "FileSink", "SocketSource",
    "LogBroker", "InMemoryLogBroker", "LogSource", "LogSink",
]
