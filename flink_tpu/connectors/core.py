"""Connector SPI: split-based sources and two-phase sinks.

Analog of flink-core's FLIP-27 / Sink V2 APIs
(api/connector/source/Source.java:33, SourceReader.java:56,
SplitEnumerator.java:34; api/connector/sink2/{Sink,SinkWriter,Committer}).
The enumerator runs on the coordinator and hands splits to per-subtask
readers; readers produce RecordBatches and snapshot their position so
checkpoints capture exact replay offsets.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

from ..core.records import MIN_TIMESTAMP, RecordBatch, Schema

__all__ = [
    "SourceSplit", "Source", "SourceReader", "Sink", "SinkWriter",
    "CollectionSource", "DataGenSource", "CollectSink", "PrintSink",
]


@dataclass(frozen=True)
class SourceSplit:
    split_id: str
    payload: Any = None


class Source:
    """Bounded or unbounded split-based source."""

    bounded: bool = True
    schema: Optional[Schema] = None

    def create_splits(self, parallelism: int) -> list[SourceSplit]:
        raise NotImplementedError

    def create_reader(self, split: SourceSplit) -> "SourceReader":
        raise NotImplementedError


class SourceReader:
    """Per-subtask reader over one split (reference SourceReader.java:56)."""

    def read_batch(self, max_records: int) -> Optional[RecordBatch]:
        """Next batch, an empty batch if nothing available right now, or
        None when the split is exhausted."""
        raise NotImplementedError

    def snapshot(self) -> Any:
        return None

    def restore(self, state: Any) -> None:
        pass

    def close(self) -> None:
        pass


class Sink:
    def create_writer(self, subtask_index: int) -> "SinkWriter":
        raise NotImplementedError


class SinkWriter:
    def write_batch(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        """Pre-commit flush at checkpoint barriers (two-phase phase 1)."""

    def prepare_commit(self, checkpoint_id: int) -> None:
        """Stage everything written so far as a committable for this
        checkpoint (reference TwoPhaseCommittingSink.PrecommittingSinkWriter
        .prepareCommit); called after flush() during the snapshot."""

    def commit(self, checkpoint_id: int) -> None:
        """Make committables up to ``checkpoint_id`` durable/visible
        (reference Committer.commit); called on checkpoint-complete
        notification. Must be idempotent — redelivery happens on recovery."""

    def snapshot(self) -> Any:
        return None

    def restore(self, state: Any) -> None:
        pass

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Built-ins
# ---------------------------------------------------------------------------

class CollectionSource(Source):
    """Bounded source over an in-memory collection (reference
    fromCollection/fromElements). Splits round-robin across subtasks."""

    def __init__(self, elements: Sequence[Any], schema: Optional[Schema] = None,
                 timestamps: Optional[Sequence[int]] = None,
                 batch_size: int = 1024):
        self._elements = list(elements)
        self.schema = schema or (Schema.infer(self._elements[0])
                                 if self._elements else Schema.of(value=object))
        self._timestamps = list(timestamps) if timestamps is not None else None
        self._batch_size = batch_size

    def create_splits(self, parallelism: int) -> list[SourceSplit]:
        return [SourceSplit(f"collection-{i}", i) for i in range(parallelism)]

    def create_reader(self, split: SourceSplit) -> SourceReader:
        stride = int(split.split_id.rsplit("-", 1)[1])
        return _CollectionReader(self, stride)

    def num_subtask_elements(self, subtask: int, parallelism: int) -> list:
        return self._elements[subtask::parallelism]


class _CollectionReader(SourceReader):
    def __init__(self, source: CollectionSource, stride_start: int):
        self._source = source
        self._stride_start = stride_start
        self._pos = 0  # position within this reader's strided view

    def _my_indices(self) -> range:
        total = len(self._source._elements)
        return range(self._stride_start, total, self._parallelism)

    _parallelism = 1  # set by the task before reading

    def read_batch(self, max_records: int) -> Optional[RecordBatch]:
        idx = list(self._my_indices())[self._pos:self._pos + max_records]
        if not idx:
            return None
        rows = [self._source._elements[i] for i in idx]
        ts = ([self._source._timestamps[i] for i in idx]
              if self._source._timestamps is not None else None)
        self._pos += len(idx)
        return RecordBatch.from_rows(self._source.schema, rows, ts)

    def snapshot(self) -> Any:
        return self._pos

    def restore(self, state: Any) -> None:
        self._pos = int(state)


class DataGenSource(Source):
    """Rate-limitable generator source (reference flink-connector-datagen):
    gen_fn(index_array) -> dict of columns. Resume is exact: the only state
    is the next index.

    ``device=True`` generates each batch ON the accelerator: ``gen_fn`` is
    traced under jit over a device index vector and the reader emits
    ``DeviceRecordBatch``es whose columns never touch the host — the
    TPU-native ingest path (data born in HBM, zero host->device transfer).
    Requires ``gen_fn`` to be jax-traceable (pure array arithmetic) and,
    when ``timestamp_column`` is set, the timestamp to be NON-DECREASING in
    the index (the event-time bounds of a batch are derived by evaluating
    ``gen_fn`` on the batch's two endpoint indices on host — checked)."""

    def __init__(self, gen_fn: Callable[[np.ndarray], dict[str, np.ndarray]],
                 schema: Schema, count: Optional[int] = None,
                 rate_per_sec: Optional[float] = None,
                 timestamp_column: Optional[str] = None,
                 device: bool = False):
        self._gen = gen_fn
        self.schema = schema
        self._count = count
        self.bounded = count is not None
        self._rate = rate_per_sec
        self._ts_col = timestamp_column
        self._device = bool(device)

    def create_splits(self, parallelism: int) -> list[SourceSplit]:
        return [SourceSplit(f"datagen-{i}", (i, parallelism))
                for i in range(parallelism)]

    def create_reader(self, split: SourceSplit) -> SourceReader:
        subtask, parallelism = split.payload
        if self._device:
            return _DeviceDataGenReader(self, subtask, parallelism)
        return _DataGenReader(self, subtask, parallelism)


class _DataGenReader(SourceReader):
    def __init__(self, source: DataGenSource, subtask: int, parallelism: int):
        self._s = source
        self._subtask = subtask
        self._parallelism = parallelism
        self._next = 0
        self._started = time.time()

    def _plan_batch(self, max_records: int) -> Optional[int]:
        """How many records the next batch may hold (None = exhausted)."""
        share = None
        if self._s._count is not None:
            total = self._s._count
            share = total // self._parallelism + (
                1 if self._subtask < total % self._parallelism else 0)
            if self._next >= share:
                return None
        n = max_records if share is None else min(max_records,
                                                  share - self._next)
        if self._s._rate is not None:
            # admission control: stay under rate_per_sec for this subtask
            allowed = int((time.time() - self._started) * self._s._rate) \
                - self._next
            n = min(n, max(allowed, 0))
        return n

    def read_batch(self, max_records: int) -> Optional[RecordBatch]:
        n = self._plan_batch(max_records)
        if n is None:
            return None
        if n == 0:
            return RecordBatch.empty(self._s.schema)
        # global indices strided by subtask for determinism under parallelism
        idx = (self._next + np.arange(n)) * self._parallelism + self._subtask
        cols = self._s._gen(idx.astype(np.int64))
        self._next += n
        batch = RecordBatch(self._s.schema, cols)
        if self._s._ts_col is not None:
            batch = batch.with_timestamps(
                batch.column(self._s._ts_col).astype(np.int64))
        return batch

    def snapshot(self) -> Any:
        return self._next

    def restore(self, state: Any) -> None:
        self._next = int(state)


class _DeviceDataGenReader(_DataGenReader):
    """Device-mode reader: one jitted program computes the batch's global
    index vector AND the user columns entirely on device; the host touches
    only two endpoint indices per batch (for event-time bounds, evaluated
    through ``gen_fn`` on a 2-element numpy array — pure host arithmetic).

    Monotonicity of the timestamp column in the index is the device-mode
    contract (the endpoint bounds depend on it). It is VERIFIED on device —
    each batch's program also reduces ``any(diff(ts) < 0)`` into a running
    device flag, checked once when the source is exhausted/closed (the
    deferred-health model of the tpu backend's ``defer_overflow``): no
    per-batch sync, still fails loudly.
    """

    # distinct jitted shapes are bounded: full batches use their exact
    # length, short batches (rate-limit slack, bounded-count tails) round
    # DOWN to a power of two — ~log2(batch) shapes total, not one per n
    _MAX_PROGS = 32

    def __init__(self, source: DataGenSource, subtask: int, parallelism: int):
        super().__init__(source, subtask, parallelism)
        self._progs: dict[int, Any] = {}   # batch length -> jitted program
        self._viol = None                  # device monotonicity violation
        self._viol_checked = False
        self._prev_last = np.int64(MIN_TIMESTAMP)  # prior batch's tail ts
        self._fused = False                # emit LazyDeviceBatch handles

    # -- fused-chain mode --------------------------------------------------
    def enable_fused(self) -> bool:
        """Switch to fused-chain emission (certified lowering only): the
        reader stops dispatching its decode program and emits
        ``LazyDeviceBatch`` handles; the downstream chained window
        operator runs decode+fold as ONE composed dispatch and hands the
        monotonicity outputs back via ``_accept_monotonic``."""
        if self._s._ts_col is None:
            return False
        self._fused = True
        return True

    def _accept_monotonic(self, viol, last) -> None:
        """Receive (violation flag, tail timestamp) for a batch whose
        decode ran downstream — same bookkeeping read_batch does in
        unfused mode. Called exactly once per batch, in emission order
        (the chain is in-task and synchronous)."""
        self._viol = viol if self._viol is None else self._viol | viol
        self._viol_checked = False
        self._prev_last = last

    def _realize_batch(self, n: int, start: int, prev_last):
        """Unfused-fallback decode for one lazy batch (degraded mode,
        validation screens, checkpoint capture): runs the ordinary
        per-batch program with the batch's creation-time tail."""
        dcols, viol, last = self._program(n)(np.int64(start), prev_last)
        ts_col = self._s._ts_col
        dts = dcols[ts_col].astype(np.int64) if ts_col is not None else None
        return dcols, dts, viol, last

    def _program(self, n: int):
        prog = self._progs.get(n)
        if prog is None:
            import jax
            import jax.numpy as jnp
            from ..ops.hash_table import ensure_x64

            ensure_x64()
            s = self._s
            stride, off = self._parallelism, self._subtask
            fields = s.schema.fields
            ts_col = s._ts_col

            @jax.jit
            def prog(start, prev_last):
                idx = (start + jnp.arange(n, dtype=jnp.int64)) * stride + off
                cols = s._gen(idx)
                out = {f.name: jnp.asarray(cols[f.name]).astype(f.dtype)
                       for f in fields}
                if ts_col is not None:
                    ts = out[ts_col]
                    # within the batch AND across the previous batch's tail
                    viol = (jnp.any(ts[1:] < ts[:-1])
                            | (ts[0].astype(jnp.int64) < prev_last))
                    last = ts[-1].astype(jnp.int64)
                else:
                    viol, last = jnp.asarray(False), prev_last
                return out, viol, last

            if len(self._progs) >= self._MAX_PROGS:
                self._progs.pop(next(iter(self._progs)))
            self._progs[n] = prog
        return prog

    def _check_monotonic(self) -> None:
        if self._viol is None or self._viol_checked:
            return
        import jax

        self._viol_checked = True
        if bool(jax.device_get(self._viol)):
            raise ValueError(
                "DataGenSource(device=True) contract violated: the "
                f"timestamp column {self._s._ts_col!r} is not "
                "non-decreasing in the index (detected on device); "
                "window results for this run are unreliable — use "
                "device=False or make gen_fn's timestamps monotonic")

    def read_batch(self, max_records: int):
        from ..core.device_records import DeviceRecordBatch

        n = self._plan_batch(max_records)
        if n is None:
            self._check_monotonic()
            return None
        if n == 0:
            return RecordBatch.empty(self._s.schema)
        if n != max_records:
            n = 1 << (n.bit_length() - 1)   # power-of-two shape bucket
        first = self._next * self._parallelism + self._subtask
        last = (self._next + n - 1) * self._parallelism + self._subtask
        if self._fused:
            from ..core.device_records import LazyDeviceBatch

            # endpoint event-time bounds on host (2-element gen_fn eval) —
            # the only per-batch work in fused mode; the decode itself is
            # composed into the window operator's single dispatch
            ts_col = self._s._ts_col
            ends = np.asarray(
                self._s._gen(np.array([first, last], np.int64))[ts_col])
            ts_min, ts_max = int(ends[0]), int(ends[1])
            if ts_min > ts_max:
                raise ValueError(
                    "DataGenSource(device=True) needs a timestamp column "
                    f"non-decreasing in the index; got ts({first})={ts_min} "
                    f"> ts({last})={ts_max}")
            batch = LazyDeviceBatch(self._s.schema, self, self._next, n,
                                    self._prev_last, ts_min, ts_max,
                                    ts_column=ts_col)
            self._next += n
            return batch
        dcols, viol, tail_ts = self._program(n)(np.int64(self._next),
                                                self._prev_last)
        self._viol = viol if self._viol is None else self._viol | viol
        self._viol_checked = False
        self._prev_last = tail_ts
        self._next += n
        ts_col = self._s._ts_col
        if ts_col is not None:
            # event-time bounds from the endpoint indices, on host (two
            # elements through the numpy path of gen_fn)
            ends = np.asarray(
                self._s._gen(np.array([first, last], np.int64))[ts_col])
            ts_min, ts_max = int(ends[0]), int(ends[1])
            if ts_min > ts_max:
                raise ValueError(
                    "DataGenSource(device=True) needs a timestamp column "
                    f"non-decreasing in the index; got ts({first})={ts_min} "
                    f"> ts({last})={ts_max}")
            return DeviceRecordBatch(self._s.schema, dcols,
                                     dcols[ts_col].astype(np.int64),
                                     ts_min, ts_max, ts_column=ts_col)
        return DeviceRecordBatch(self._s.schema, dcols, None,
                                 MIN_TIMESTAMP, MIN_TIMESTAMP)

    def close(self) -> None:
        self._check_monotonic()

    # -- checkpointing: the deferred violation flag and the cross-batch
    # tail timestamp are part of the reader's exact-resume state ---------
    def snapshot(self) -> Any:
        import jax

        viol = (bool(jax.device_get(self._viol))
                if self._viol is not None else False)
        return {"next": self._next, "prev_last": int(self._prev_last),
                "viol": viol}

    def restore(self, state: Any) -> None:
        if isinstance(state, dict):
            self._next = int(state["next"])
            self._prev_last = np.int64(state["prev_last"])
            if state.get("viol"):
                # the violation predates this checkpoint; resuming would
                # silently launder it
                raise ValueError(
                    "DataGenSource(device=True) checkpoint records a "
                    "timestamp-monotonicity contract violation; the job's "
                    "window results are unreliable — fix gen_fn")
        else:  # pre-upgrade snapshot: bare index
            self._next = int(state)


class CollectSink(Sink):
    """Collects rows into a shared list — the test/ITCase sink
    (reference DataStream.executeAndCollect)."""

    def __init__(self):
        self.rows: list = []
        import threading
        self._lock = threading.Lock()

    def create_writer(self, subtask_index: int) -> SinkWriter:
        sink = self

        class _W(SinkWriter):
            def write_batch(self, batch: RecordBatch) -> None:
                with sink._lock:
                    sink.rows.extend(batch.iter_rows())

        return _W()


class PrintSink(Sink):
    def __init__(self, prefix: str = ""):
        self._prefix = prefix

    def create_writer(self, subtask_index: int) -> SinkWriter:
        prefix = self._prefix

        class _W(SinkWriter):
            def write_batch(self, batch: RecordBatch) -> None:
                for row in batch.iter_rows():
                    print(f"{prefix}{row}")

        return _W()
