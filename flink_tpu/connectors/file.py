"""File source/sink with exactly-once commit, over the FileSystem SPI.

Analogs of the reference's flink-connector-files:
* FileSource (FLIP-27: one split per file with a byte/line offset so
  checkpoints capture exact replay positions — reference
  FileSource/FileSourceSplit) over any text or binary Format;
* FileSink with the in-progress -> pending -> committed protocol of the
  reference's FileSink/StreamingFileSink: records append to a hidden
  ``.part-*.inprogress`` file, each checkpoint stages it as pending
  (prepare_commit), and the checkpoint-complete notification atomically
  renames pending files to visible part files (commit). Uncommitted temp
  files from a crashed attempt are ignored by readers and cleaned on
  restart.

Paths resolve through core/fs.py (reference core/fs/FileSystem.java), so
``mem://`` object-store-style paths work everywhere local paths do —
including SQL filesystem tables — and new schemes arrive as plugins.
"""

from __future__ import annotations

import fnmatch
import glob as _glob
import io
import os
from typing import Any, Optional

from ..core.fs import get_file_system
from ..core.records import RecordBatch, Schema
from ..formats.core import Format
from .core import Sink, SinkWriter, Source, SourceReader, SourceSplit

__all__ = ["FileSource", "FileSink"]


def _join(base: str, name: str) -> str:
    return base.rstrip("/") + "/" + name


class FileSource(Source):
    """Bounded source over a file path, directory, or glob; one split per
    file, files distributed round-robin across subtasks."""

    bounded = True

    def __init__(self, path: str, fmt: Format, batch_lines: int = 4096):
        self._path = path
        self._fmt = fmt
        self.schema = fmt.schema
        self._batch_lines = batch_lines

    def _files(self) -> list[str]:
        fs, p = get_file_system(self._path)
        if fs.is_dir(p):
            return [
                _join(self._path, n) for n in fs.listdir(p)
                if not n.startswith(".") and not n.endswith(".inprogress")
                and not fs.is_dir(_join(p, n))]
        if "://" not in self._path:
            matches = sorted(_glob.glob(self._path))
            if matches:
                return matches
        elif fs.exists(p) and not any(c in p for c in "*?["):
            return [self._path]
        else:
            # scheme glob: match the last segment against the parent's
            # listing (object stores have no native glob)
            parent, _, pattern = p.rpartition("/")
            scheme = self._path.split("://", 1)[0]
            if parent and fs.is_dir(parent):
                matches = sorted(
                    f"{scheme}://{parent}/{n}" for n in fs.listdir(parent)
                    if fnmatch.fnmatch(n, pattern)
                    and not fs.is_dir(_join(parent, n)))
                if matches:
                    return matches
        raise FileNotFoundError(self._path)

    def create_splits(self, parallelism: int) -> list[SourceSplit]:
        files = self._files()
        return [SourceSplit(f"files-{i}", files[i::parallelism])
                for i in range(parallelism)]

    def create_reader(self, split: SourceSplit) -> SourceReader:
        return _FileReader(self._fmt, split.payload, self._batch_lines)


class _FileReader(SourceReader):
    """Reads this subtask's files in order; state = (file index, position)
    where position is a line number (text) or byte offset (binary)."""

    def __init__(self, fmt: Format, files: list, batch_lines: int):
        self._fmt = fmt
        self._files = list(files)
        self._batch = batch_lines
        self._file_idx = 0
        self._pos = 0
        self._pending = b""  # binary carry-over

    def read_batch(self, max_records: int) -> Optional[RecordBatch]:
        while self._file_idx < len(self._files):
            path = self._files[self._file_idx]
            if getattr(self._fmt, "whole_file", False):
                batch = self._read_whole_file(path)
            elif self._fmt.binary:
                batch = self._read_binary(path)
            else:
                batch = self._read_text(path)
            if batch is not None:
                return batch
            self._file_idx += 1
            self._pos = 0
            self._pending = b""
        return None

    def _read_whole_file(self, path: str) -> Optional[RecordBatch]:
        """Whole-file formats (parquet): position = row-group index, so
        checkpoint resume restarts at group granularity."""
        fs, p = get_file_system(path)
        with fs.open_read(p) as f:
            batches, nxt, eof = self._fmt.read_row_groups(
                f, self._pos, max_groups=1)
        self._pos = nxt
        if not batches:
            return None
        return batches[0] if len(batches) == 1 else \
            RecordBatch.concat(batches)

    def _read_text(self, path: str) -> Optional[RecordBatch]:
        """Reads by byte offset (seek + readline) so resuming and batching
        stay O(batch), not O(file)."""
        at_start = self._pos == 0
        fs, p = get_file_system(path)
        with fs.open_read(p) as f:
            f.seek(self._pos)
            lines = []
            for _ in range(self._batch):
                ln = f.readline()
                if not ln:
                    break
                lines.append(ln.decode("utf-8").rstrip("\n"))
            self._pos = f.tell()
        if not lines:
            return None
        if at_start and getattr(self._fmt, "skip_header", False):
            lines = lines[1:]
        return self._fmt.decode_lines(lines)

    def _read_binary(self, path: str) -> Optional[RecordBatch]:
        fs, p = get_file_system(path)
        with fs.open_read(p) as f:
            f.seek(self._pos)
            data = self._pending + f.read(1 << 20)
            if not data:
                return None
            self._pos = f.tell()
        batches, self._pending = self._fmt.decode_block(data)
        if not batches:
            return None
        return RecordBatch.concat(batches)

    def snapshot(self) -> Any:
        return {"file": self._file_idx, "pos": self._pos}

    def restore(self, state: Any) -> None:
        self._file_idx = int(state["file"])
        self._pos = int(state["pos"])
        self._pending = b""


class FileSink(Sink):
    """Exactly-once rolling file sink (reference FileSink)."""

    def __init__(self, directory: str, fmt: Format,
                 rolling_size: int = 64 << 20, part_prefix: str = "part"):
        self._dir = directory
        self._fmt = fmt
        self._rolling_size = rolling_size
        self._prefix = part_prefix

    def create_writer(self, subtask_index: int) -> SinkWriter:
        fs, p = get_file_system(self._dir)
        fs.makedirs(p)
        return _FileWriter(self._dir, self._fmt, subtask_index,
                           self._rolling_size, self._prefix)


class _FileWriter(SinkWriter):
    def __init__(self, directory: str, fmt: Format, subtask: int,
                 rolling_size: int, prefix: str):
        self._dir = directory
        self._fs, self._dir_path = get_file_system(directory)
        self._fmt = fmt
        self._subtask = subtask
        self._rolling = rolling_size
        self._prefix = prefix
        self._seq = 0
        self._fh = None
        self._in_progress: Optional[str] = None
        # pending[checkpoint_id] -> [(tmp_path, final_path)]  (fs-relative)
        self._pending: dict[int, list[tuple[str, str]]] = {}
        self._cleaned = False

    def _clean_stale(self) -> None:
        """Drop in-progress temp files from a crashed attempt of THIS
        subtask (committed parts are never touched). Runs lazily on first
        write — i.e. AFTER restore() has committed restored pending files —
        and skips anything still registered as pending."""
        self._cleaned = True
        keep = {tmp for entries in self._pending.values()
                for tmp, _ in entries}
        pat = f".{self._prefix}-{self._subtask}-*.inprogress"
        try:
            names = self._fs.listdir(self._dir_path)
        except OSError:
            return
        for name in names:
            if not fnmatch.fnmatch(name, pat):
                continue
            stale = _join(self._dir_path, name)
            if stale not in keep:
                try:
                    self._fs.remove(stale)
                except OSError:
                    pass

    def _open(self) -> None:
        if not self._cleaned:
            self._clean_stale()
        final = f"{self._prefix}-{self._subtask}-{self._seq}"
        self._in_progress = _join(self._dir_path, f".{final}.inprogress")
        self._final = _join(self._dir_path, final)
        self._fh = self._fs.open_write(self._in_progress, append=True)
        self._seq += 1

    def write_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        if self._fh is None:
            self._open()
            if getattr(self._fmt, "whole_file", False):
                self._session = self._fmt.open_writer(self._fh)
        if getattr(self, "_session", None) is not None:
            self._session.write(batch)
        elif self._fmt.binary:
            self._fh.write(self._fmt.encode_block(batch))
        else:
            self._fh.write(self._fmt.encode_batch(batch).encode("utf-8"))
        if self._fh.tell() >= self._rolling:
            self._roll_pending_file(checkpoint_id=None)

    def _roll_pending_file(self, checkpoint_id: Optional[int]) -> None:
        """Close the current in-progress file; it becomes committable at the
        NEXT prepare_commit (size-based rolls stage under key None)."""
        if self._fh is None:
            return
        session = getattr(self, "_session", None)
        if session is not None:
            session.close()        # parquet footer before the rename
            self._session = None
        self._fh.close()
        self._pending.setdefault(-1 if checkpoint_id is None
                                 else checkpoint_id, []).append(
            (self._in_progress, self._final))
        self._fh = None
        self._in_progress = None

    def flush(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            try:
                os.fsync(self._fh.fileno())
            except io.UnsupportedOperation:
                pass  # memory-backed streams have no fd to sync; a REAL
                # fsync failure (EIO) must still fail the checkpoint

    def prepare_commit(self, checkpoint_id: int) -> None:
        self._roll_pending_file(checkpoint_id)
        # size-rolled files (key -1) ride along with this checkpoint
        rolled = self._pending.pop(-1, [])
        if rolled:
            self._pending.setdefault(checkpoint_id, []).extend(rolled)

    def commit(self, checkpoint_id: int) -> None:
        # key -1 holds size-rolled files not yet staged by a prepare_commit:
        # they contain post-barrier records and must NOT commit yet
        for cid in sorted(k for k in self._pending
                          if 0 <= k <= checkpoint_id):
            for tmp, final in self._pending.pop(cid):
                if self._fs.exists(tmp):
                    self._fs.rename(tmp, final)  # atomic, idempotent on redo
        # recovery redelivery: a committed tmp no longer exists -> no-op

    def snapshot(self) -> Any:
        return {"seq": self._seq,
                "pending": {cid: list(v)
                            for cid, v in self._pending.items()}}

    def restore(self, state: Any) -> None:
        self._seq = int(state["seq"])
        # pending files from the snapshot are committed on restore (their
        # checkpoint completed iff we restored from it — reference
        # FileSink committer recovery)
        for cid, entries in state.get("pending", {}).items():
            for tmp, final in entries:
                if self._fs.exists(tmp):
                    self._fs.rename(tmp, final)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
