"""Socket text source (reference SocketTextStreamFunction /
env.socketTextStream): unbounded newline-delimited text over TCP, with
reconnect backoff. Single-split (the reference's socket source is
parallelism-1); other subtasks get an idle split.
"""

from __future__ import annotations

import socket
import time
from typing import Any, Optional

import numpy as np

from ..core.records import RecordBatch, Schema
from .core import Source, SourceReader, SourceSplit

__all__ = ["SocketSource"]


class SocketSource(Source):
    bounded = False

    def __init__(self, host: str, port: int,
                 schema: Optional[Schema] = None,
                 max_retries: int = 3, retry_delay: float = 0.5):
        self._host = host
        self._port = port
        self.schema = schema or Schema([("line", object)])
        self._max_retries = max_retries
        self._retry_delay = retry_delay

    def create_splits(self, parallelism: int) -> list[SourceSplit]:
        return [SourceSplit(f"socket-{i}", i == 0)
                for i in range(parallelism)]

    def create_reader(self, split: SourceSplit) -> SourceReader:
        if not split.payload:
            return _IdleReader(self.schema)
        return _SocketReader(self._host, self._port, self.schema,
                             self._max_retries, self._retry_delay)


class _IdleReader(SourceReader):
    """Non-lead subtasks of a parallelism-1-style source idle forever."""

    def __init__(self, schema: Schema):
        self._schema = schema

    def read_batch(self, max_records: int) -> Optional[RecordBatch]:
        return RecordBatch.empty(self._schema)


class _SocketReader(SourceReader):
    def __init__(self, host: str, port: int, schema: Schema,
                 max_retries: int, retry_delay: float):
        self._host = host
        self._port = port
        self._schema = schema
        self._max_retries = max_retries
        self._retry_delay = retry_delay
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._overflow: list[str] = []  # decoded lines beyond max_records
        self._retries = 0
        self._eof = False

    def _connect(self) -> bool:
        try:
            self._sock = socket.create_connection(
                (self._host, self._port), timeout=1.0)
            self._sock.setblocking(False)
            self._retries = 0
            return True
        except OSError:
            self._sock = None
            self._retries += 1
            if self._retries > self._max_retries:
                self._eof = True
            else:
                time.sleep(self._retry_delay)
            return False

    def read_batch(self, max_records: int) -> Optional[RecordBatch]:
        if self._eof and not self._buf and not self._overflow:
            return None
        if self._sock is None and not self._eof:
            if not self._connect():
                return RecordBatch.empty(self._schema)
        data = b""
        if self._sock is not None:
            try:
                data = self._sock.recv(1 << 16)
                if data == b"":  # orderly close
                    self._sock.close()
                    self._sock = None
                    self._eof = True
            except BlockingIOError:
                pass
            except OSError:
                self._sock = None  # reconnect next call
        self._buf += data
        rows = self._overflow
        self._overflow = []
        if b"\n" in self._buf or (self._eof and self._buf):
            *lines, self._buf = self._buf.split(b"\n")
            if self._eof and self._buf:
                lines.append(self._buf)
                self._buf = b""
            rows += [ln.decode("utf-8", "replace") for ln in lines if ln]
        if not rows:
            return RecordBatch.empty(self._schema)
        if max_records and len(rows) > max_records:
            rows, self._overflow = rows[:max_records], rows[max_records:]
        col = np.array(rows, dtype=object)
        return RecordBatch(self._schema,
                           {self._schema.fields[0].name: col})

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
