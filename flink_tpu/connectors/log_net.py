"""Network-backed log broker: the LogBroker contract over TCP.

The in-memory broker (connectors/log.py) serves single-process tests; this
pair makes the Kafka-shaped connector real across processes and hosts —
``LogBrokerServer`` hosts topics (backed by an InMemoryLogBroker), and
``RemoteLogBroker`` is a client implementing the same ``LogBroker``
interface, so LogSource/LogSink work unchanged (reference: the Kafka
cluster stands behind KafkaSource/KafkaSink the same way). Framing is the
data plane's length-prefixed pickle (cluster/transport.py style); each
client connection is served by its own thread, state lives in the broker
under its lock.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional

from .log import InMemoryLogBroker, LogBroker

__all__ = ["LogBrokerServer", "RemoteLogBroker"]

_MSG = struct.Struct("<I")


def _send(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_MSG.pack(len(payload)) + payload)


def _recv(sock: socket.socket) -> Optional[Any]:
    head = b""
    while len(head) < _MSG.size:
        chunk = sock.recv(_MSG.size - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _MSG.unpack(head)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            return None
        body += chunk
    return pickle.loads(body)


class LogBrokerServer:
    """Serves a LogBroker over TCP. Methods are dispatched by name —
    exactly the LogBroker surface, nothing else."""

    _ALLOWED = {"partitions", "poll", "append", "append_txn", "end_offset",
                "create_topic"}

    def __init__(self, backing: Optional[LogBroker] = None, port: int = 0,
                 host: str = "127.0.0.1", config=None):
        from ..utils import auth

        self.broker = backing or InMemoryLogBroker()
        self._secret = auth.resolve_secret(config)
        auth.check_bind(host, self._secret, "LogBrokerServer")
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        threading.Thread(target=self._accept, name="log-broker-accept",
                         daemon=True).start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="log-broker-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        from ..utils import auth

        try:
            # the auth preamble precedes the FIRST pickle read: a caller
            # without the cluster secret never reaches pickle.loads
            if not auth.recv_hello(conn, self._secret):
                return
            while not self._stop.is_set():
                msg = _recv(conn)
                if msg is None:
                    return
                method, args = msg
                try:
                    if method not in self._ALLOWED:
                        raise AttributeError(f"no broker method {method!r}")
                    result = getattr(self.broker, method)(*args)
                    _send(conn, ("ok", result))
                except Exception as e:  # noqa: BLE001 - shipped to client
                    _send(conn, ("err", f"{type(e).__name__}: {e}"))
        except OSError:
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def drop_connections(self) -> None:
        """Sever every live client connection (listener stays up) — the
        broker-restart simulation clients must survive by reconnecting."""
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self.drop_connections()


class RemoteLogBroker(LogBroker):
    """TCP client implementing LogBroker. Calls serialize under a lock on
    one connection (parallel subtasks sharing an instance contend — the
    correctness tradeoff of the simple framing; heavy fan-in should give
    each reader its own instance). There is no request id on the wire, so
    after ANY send/recv failure the connection may hold a stale response —
    it is closed immediately and the next call reconnects fresh."""

    def __init__(self, address: str, connect_timeout: float = 5.0,
                 config=None):
        from ..utils import auth

        self._address = address
        self._connect_timeout = connect_timeout
        self._secret = auth.resolve_secret(config)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        from ..utils import auth

        host, port = self._address.rsplit(":", 1)
        self._sock = socket.create_connection(
            (host, int(port)), timeout=self._connect_timeout)
        self._sock.settimeout(30.0)
        auth.send_hello(self._sock, self._secret)

    def _call(self, method: str, *args):
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                _send(self._sock, (method, args))
                resp = _recv(self._sock)
            except (OSError, ConnectionError):
                # the stream may now hold a half-written request or an
                # unread response: poison — drop the connection so the
                # next call starts clean instead of reading stale frames
                self._teardown()
                raise
            if resp is None:
                self._teardown()
                raise ConnectionError("log broker connection closed")
        status, payload = resp
        if status == "err":
            raise RuntimeError(f"broker error: {payload}")
        return payload

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def create_topic(self, topic: str,
                     num_partitions: Optional[int] = None) -> None:
        self._call("create_topic", topic, num_partitions)

    def partitions(self, topic: str) -> int:
        return self._call("partitions", topic)

    def poll(self, topic, partition, offset, max_records):
        return self._call("poll", topic, partition, offset, max_records)

    def append(self, topic, partition, payloads) -> None:
        self._call("append", topic, partition, payloads)

    def append_txn(self, txn_id, topic, partition, payloads) -> None:
        self._call("append_txn", txn_id, topic, partition, payloads)

    def end_offset(self, topic, partition) -> int:
        return self._call("end_offset", topic, partition)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
