"""Network-backed log broker: the LogBroker contract over TCP.

The in-memory broker (connectors/log.py) serves single-process tests; this
pair makes the Kafka-shaped connector real across processes and hosts —
``LogBrokerServer`` hosts topics (backed by an InMemoryLogBroker), and
``RemoteLogBroker`` is a client implementing the same ``LogBroker``
interface, so LogSource/LogSink work unchanged (reference: the Kafka
cluster stands behind KafkaSource/KafkaSink the same way). Framing is the
data plane's length-prefixed pickle (cluster/transport.py style); each
client connection is served by its own thread, state lives in the broker
under its lock.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional

from .log import InMemoryLogBroker, LogBroker

__all__ = ["LogBrokerServer", "RemoteLogBroker"]

_MSG = struct.Struct("<I")


def _send(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_MSG.pack(len(payload)) + payload)


def _recv(sock: socket.socket) -> Optional[Any]:
    head = b""
    while len(head) < _MSG.size:
        chunk = sock.recv(_MSG.size - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _MSG.unpack(head)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            return None
        body += chunk
    return pickle.loads(body)


class LogBrokerServer:
    """Serves a LogBroker over TCP. Methods are dispatched by name —
    exactly the LogBroker surface, nothing else."""

    _ALLOWED = {"partitions", "poll", "append", "append_txn", "end_offset",
                "create_topic"}

    def __init__(self, backing: Optional[LogBroker] = None, port: int = 0,
                 host: str = "127.0.0.1"):
        self.broker = backing or InMemoryLogBroker()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        threading.Thread(target=self._accept, name="log-broker-accept",
                         daemon=True).start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name="log-broker-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                msg = _recv(conn)
                if msg is None:
                    return
                method, args = msg
                try:
                    if method not in self._ALLOWED:
                        raise AttributeError(f"no broker method {method!r}")
                    result = getattr(self.broker, method)(*args)
                    _send(conn, ("ok", result))
                except Exception as e:  # noqa: BLE001 - shipped to client
                    _send(conn, ("err", f"{type(e).__name__}: {e}"))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class RemoteLogBroker(LogBroker):
    """TCP client implementing LogBroker; one connection per instance,
    calls serialized under a lock (readers/writers each own an instance)."""

    def __init__(self, address: str, connect_timeout: float = 5.0):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=connect_timeout)
        self._sock.settimeout(30.0)
        self._lock = threading.Lock()

    def _call(self, method: str, *args):
        with self._lock:
            _send(self._sock, (method, args))
            resp = _recv(self._sock)
        if resp is None:
            raise ConnectionError("log broker connection closed")
        status, payload = resp
        if status == "err":
            raise RuntimeError(f"broker error: {payload}")
        return payload

    def create_topic(self, topic: str,
                     num_partitions: Optional[int] = None) -> None:
        self._call("create_topic", topic, num_partitions)

    def partitions(self, topic: str) -> int:
        return self._call("partitions", topic)

    def poll(self, topic, partition, offset, max_records):
        return self._call("poll", topic, partition, offset, max_records)

    def append(self, topic, partition, payloads) -> None:
        self._call("append", topic, partition, payloads)

    def append_txn(self, txn_id, topic, partition, payloads) -> None:
        self._call("append_txn", txn_id, topic, partition, payloads)

    def end_offset(self, topic, partition) -> int:
        return self._call("end_offset", topic, partition)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
