"""Partitioned-log (Kafka-shaped) source and sink.

The reference externalizes its Kafka connector, but its shape — topics of
ordered partitions consumed by partition-offset splits, transactional
produce — is the canonical streaming connector contract (FLIP-27 splits =
(topic, partition, offset); KafkaSource/KafkaSink). This module implements
that contract against a pluggable ``LogBroker`` so the semantics (partition
assignment, offset checkpointing, exactly-once transactional produce) are
real and testable without a Kafka client; a network-backed broker drops in
behind the same interface.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import numpy as np

from ..core.records import RecordBatch, Schema
from ..formats.core import Format
from .core import Sink, SinkWriter, Source, SourceReader, SourceSplit

__all__ = ["LogBroker", "InMemoryLogBroker", "LogSource", "LogSink"]


class LogBroker:
    """Minimal partitioned-log API (the Kafka client surface we consume)."""

    def partitions(self, topic: str) -> int:
        raise NotImplementedError

    def poll(self, topic: str, partition: int, offset: int,
             max_records: int) -> list[tuple[int, str]]:
        """[(offset, payload), ...] starting at ``offset``."""
        raise NotImplementedError

    def append(self, topic: str, partition: int,
               payloads: list[str]) -> None:
        raise NotImplementedError

    def append_txn(self, txn_id: str, topic: str, partition: int,
                   payloads: list[str]) -> None:
        """Idempotent append: a txn_id that was already applied is a no-op
        (the Kafka transactional-producer contract exactly-once sinks
        need)."""
        raise NotImplementedError

    def end_offset(self, topic: str, partition: int) -> int:
        raise NotImplementedError


class InMemoryLogBroker(LogBroker):
    """Process-local broker for tests/ITCases (the MiniCluster of brokers)."""

    def __init__(self, num_partitions: int = 4):
        self._topics: dict[str, list[list[str]]] = {}
        self._n = num_partitions
        self._applied_txns: set[str] = set()
        self._lock = threading.Lock()

    def create_topic(self, topic: str,
                     num_partitions: Optional[int] = None) -> None:
        with self._lock:
            self._topics.setdefault(
                topic, [[] for _ in range(num_partitions or self._n)])

    def partitions(self, topic: str) -> int:
        return len(self._topics[topic])

    def poll(self, topic: str, partition: int, offset: int,
             max_records: int) -> list[tuple[int, str]]:
        with self._lock:
            log = self._topics[topic][partition]
            end = min(len(log), offset + max_records)
            return [(o, log[o]) for o in range(offset, end)]

    def append(self, topic: str, partition: int,
               payloads: list[str]) -> None:
        with self._lock:
            self._topics.setdefault(
                topic, [[] for _ in range(self._n)])[partition].extend(
                payloads)

    def append_txn(self, txn_id: str, topic: str, partition: int,
                   payloads: list[str]) -> None:
        with self._lock:
            if txn_id in self._applied_txns:
                return
            self._applied_txns.add(txn_id)
            self._topics.setdefault(
                topic, [[] for _ in range(self._n)])[partition].extend(
                payloads)

    def end_offset(self, topic: str, partition: int) -> int:
        with self._lock:
            return len(self._topics[topic][partition])


class LogSource(Source):
    """Splits = partitions, distributed round-robin over subtasks; reader
    state = next offset per partition (exact replay on restore)."""

    def __init__(self, broker: LogBroker, topic: str, fmt: Format,
                 bounded: bool = False,
                 starting_offsets: str = "earliest"):
        self._broker = broker
        self._topic = topic
        self._fmt = fmt
        self.schema = fmt.schema
        self.bounded = bounded
        self._start = starting_offsets

    def create_splits(self, parallelism: int) -> list[SourceSplit]:
        parts = list(range(self._broker.partitions(self._topic)))
        return [SourceSplit(f"{self._topic}-{i}", parts[i::parallelism])
                for i in range(parallelism)]

    def create_reader(self, split: SourceSplit) -> SourceReader:
        return _LogReader(self._broker, self._topic, self._fmt,
                          split.payload, self.bounded, self._start)


class _LogReader(SourceReader):
    def __init__(self, broker: LogBroker, topic: str, fmt: Format,
                 partitions: list, bounded: bool, start: str):
        self._b = broker
        self._topic = topic
        self._fmt = fmt
        self._parts = list(partitions)
        self._bounded = bounded
        self._offsets = {
            p: (0 if start == "earliest"
                else broker.end_offset(topic, p))
            for p in self._parts}
        self._rr = 0

    def read_batch(self, max_records: int) -> Optional[RecordBatch]:
        if not self._parts:
            return None if self._bounded else RecordBatch.empty(
                self._fmt.schema)
        done = 0
        for _ in range(len(self._parts)):
            p = self._parts[self._rr % len(self._parts)]
            self._rr += 1
            recs = self._b.poll(self._topic, p, self._offsets[p],
                                max_records)
            if recs:
                self._offsets[p] = recs[-1][0] + 1
                return self._fmt.decode_lines([r for _, r in recs])
            if self._offsets[p] >= self._b.end_offset(self._topic, p):
                done += 1
        if self._bounded and done == len(self._parts):
            return None
        return RecordBatch.empty(self._fmt.schema)

    def snapshot(self) -> Any:
        return dict(self._offsets)

    def restore(self, state: Any) -> None:
        self._offsets.update({int(k): int(v) for k, v in state.items()})


class LogSink(Sink):
    """Transactional produce: records buffer per checkpoint epoch and only
    append to the broker on checkpoint-complete (the reference KafkaSink's
    EXACTLY_ONCE transactional semantics, with the broker append standing in
    for transaction commit)."""

    def __init__(self, broker: LogBroker, topic: str, fmt: Format,
                 partition_by: Optional[str] = None):
        self._broker = broker
        self._topic = topic
        self._fmt = fmt
        self._partition_by = partition_by

    def create_writer(self, subtask_index: int) -> SinkWriter:
        return _LogWriter(self._broker, self._topic, self._fmt,
                          self._partition_by, subtask_index)


class _LogWriter(SinkWriter):
    def __init__(self, broker: LogBroker, topic: str, fmt: Format,
                 partition_by: Optional[str], subtask: int):
        self._b = broker
        self._topic = topic
        self._fmt = fmt
        self._partition_by = partition_by
        self._subtask = subtask
        self._open_lines: dict[int, list[str]] = {}    # partition -> lines
        self._staged: dict[int, dict[int, list[str]]] = {}  # ckpt -> part

    def _partition_of(self, row, n_parts: int) -> int:
        if self._partition_by is None:
            return self._subtask % n_parts
        idx = self._fmt.schema.index_of(self._partition_by)
        v = row[idx] if isinstance(row, tuple) else row
        # stable across restarts (Python's hash() is salted per process)
        from ..core.keygroups import stable_hash
        return stable_hash(v) % n_parts

    def write_batch(self, batch: RecordBatch) -> None:
        if batch.n == 0:
            return
        n_parts = self._b.partitions(self._topic)
        text = self._fmt.encode_batch(batch).rstrip("\n")
        lines = text.split("\n") if text else []
        for row, line in zip(batch.iter_rows(), lines):
            p = self._partition_of(row, n_parts)
            self._open_lines.setdefault(p, []).append(line)

    def prepare_commit(self, checkpoint_id: int) -> None:
        if self._open_lines:
            self._staged[checkpoint_id] = self._open_lines
            self._open_lines = {}

    def _txn_id(self, cid, partition: int) -> str:
        return f"{self._topic}/{self._subtask}/{cid}/{partition}"

    def commit(self, checkpoint_id: int) -> None:
        for cid in sorted(k for k in self._staged if k <= checkpoint_id):
            for p, lines in self._staged.pop(cid).items():
                # txn id makes redelivery after recovery a no-op
                self._b.append_txn(self._txn_id(cid, p), self._topic, p,
                                   lines)

    def snapshot(self) -> Any:
        return {"staged": {cid: {p: list(ls) for p, ls in parts.items()}
                           for cid, parts in self._staged.items()}}

    def restore(self, state: Any) -> None:
        # staged epochs from the restored checkpoint commit now (their
        # checkpoint completed iff we restored from it); append_txn dedups
        # epochs the pre-crash attempt already committed
        for cid, parts in state.get("staged", {}).items():
            for p, lines in parts.items():
                self._b.append_txn(self._txn_id(cid, int(p)), self._topic,
                                   int(p), list(lines))
