"""Window triggers and evictors.

Analog of flink-streaming-java api/windowing/triggers/
(EventTimeTrigger, ProcessingTimeTrigger, CountTrigger, PurgingTrigger,
ContinuousEventTimeTrigger, Trigger.TriggerContext) and
api/windowing/evictors/ (CountEvictor, TimeEvictor).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Optional

__all__ = [
    "TriggerResult", "Trigger", "TriggerContext", "EventTimeTrigger",
    "ProcessingTimeTrigger", "CountTrigger", "PurgingTrigger", "NeverTrigger",
    "ContinuousEventTimeTrigger", "Evictor", "CountEvictor", "TimeEvictor",
]


class TriggerResult(enum.Flag):
    CONTINUE = 0
    FIRE = enum.auto()
    PURGE = enum.auto()
    FIRE_AND_PURGE = FIRE | PURGE

    @property
    def fires(self) -> bool:
        return bool(self & TriggerResult.FIRE)

    @property
    def purges(self) -> bool:
        return bool(self & TriggerResult.PURGE)


class TriggerContext:
    """What a trigger can do (reference Trigger.TriggerContext): timers +
    per-(key,window) trigger state. Provided by the window operator."""

    def __init__(self, key, window, timer_service, state_accessor,
                 current_watermark: int):
        self.key = key
        self.window = window
        self._timers = timer_service
        self._state = state_accessor
        self.current_watermark = current_watermark

    def register_event_time_timer(self, ts: int) -> None:
        self._timers.register_event_time_timer(self.key, ts, self.window)

    def register_processing_time_timer(self, ts: int) -> None:
        self._timers.register_processing_time_timer(self.key, ts, self.window)

    def delete_event_time_timer(self, ts: int) -> None:
        self._timers.delete_event_time_timer(self.key, ts, self.window)

    def delete_processing_time_timer(self, ts: int) -> None:
        self._timers.delete_processing_time_timer(self.key, ts, self.window)

    def get_trigger_state(self, name: str, default: Any = None) -> Any:
        return self._state.get(name, default)

    def set_trigger_state(self, name: str, value: Any) -> None:
        self._state.set(name, value)

    def clear_trigger_state(self, name: str) -> None:
        self._state.clear(name)


class Trigger:
    def on_element(self, timestamp: int, window, ctx: TriggerContext) -> TriggerResult:
        return TriggerResult.CONTINUE

    def on_event_time(self, time: int, window, ctx: TriggerContext) -> TriggerResult:
        return TriggerResult.CONTINUE

    def on_processing_time(self, time: int, window,
                           ctx: TriggerContext) -> TriggerResult:
        return TriggerResult.CONTINUE

    def clear(self, window, ctx: TriggerContext) -> None:
        pass

    def can_merge(self) -> bool:
        return False

    def on_merge(self, window, ctx: TriggerContext) -> None:
        pass


class EventTimeTrigger(Trigger):
    """Fire once the watermark passes window end (reference EventTimeTrigger)."""

    def on_element(self, timestamp, window, ctx):
        if window.max_timestamp <= ctx.current_watermark:
            return TriggerResult.FIRE
        ctx.register_event_time_timer(window.max_timestamp)
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx):
        return TriggerResult.FIRE if time == window.max_timestamp \
            else TriggerResult.CONTINUE

    def clear(self, window, ctx):
        ctx.delete_event_time_timer(window.max_timestamp)

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx):
        if window.max_timestamp > ctx.current_watermark:
            ctx.register_event_time_timer(window.max_timestamp)


class ProcessingTimeTrigger(Trigger):
    def on_element(self, timestamp, window, ctx):
        ctx.register_processing_time_timer(window.max_timestamp)
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx):
        return TriggerResult.FIRE

    def clear(self, window, ctx):
        ctx.delete_processing_time_timer(window.max_timestamp)

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx):
        ctx.register_processing_time_timer(window.max_timestamp)


@dataclass
class CountTrigger(Trigger):
    """Fire every N elements (reference CountTrigger)."""

    max_count: int

    @staticmethod
    def of(n: int) -> "CountTrigger":
        return CountTrigger(n)

    def on_element(self, timestamp, window, ctx):
        count = ctx.get_trigger_state("count", 0) + 1
        if count >= self.max_count:
            ctx.clear_trigger_state("count")
            return TriggerResult.FIRE
        ctx.set_trigger_state("count", count)
        return TriggerResult.CONTINUE

    def clear(self, window, ctx):
        ctx.clear_trigger_state("count")


@dataclass
class ContinuousEventTimeTrigger(Trigger):
    """Fire at a fixed event-time interval while the window is open."""

    interval: int

    @staticmethod
    def of(interval_ms: int) -> "ContinuousEventTimeTrigger":
        return ContinuousEventTimeTrigger(interval_ms)

    def on_element(self, timestamp, window, ctx):
        if window.max_timestamp <= ctx.current_watermark:
            return TriggerResult.FIRE
        ctx.register_event_time_timer(window.max_timestamp)
        if ctx.get_trigger_state("next-fire") is None:
            next_fire = timestamp - (timestamp % self.interval) + self.interval
            ctx.set_trigger_state("next-fire", next_fire)
            ctx.register_event_time_timer(next_fire)
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx):
        if time == window.max_timestamp:
            return TriggerResult.FIRE
        next_fire = ctx.get_trigger_state("next-fire")
        if next_fire == time:
            ctx.set_trigger_state("next-fire", time + self.interval)
            ctx.register_event_time_timer(time + self.interval)
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def clear(self, window, ctx):
        ctx.delete_event_time_timer(window.max_timestamp)
        nf = ctx.get_trigger_state("next-fire")
        if nf is not None:
            ctx.delete_event_time_timer(nf)
            ctx.clear_trigger_state("next-fire")


@dataclass
class PurgingTrigger(Trigger):
    """Wraps a trigger so every FIRE becomes FIRE_AND_PURGE."""

    inner: Trigger

    @staticmethod
    def of(inner: Trigger) -> "PurgingTrigger":
        return PurgingTrigger(inner)

    def on_element(self, timestamp, window, ctx):
        return self._purge(self.inner.on_element(timestamp, window, ctx))

    def on_event_time(self, time, window, ctx):
        return self._purge(self.inner.on_event_time(time, window, ctx))

    def on_processing_time(self, time, window, ctx):
        return self._purge(self.inner.on_processing_time(time, window, ctx))

    def clear(self, window, ctx):
        self.inner.clear(window, ctx)

    @staticmethod
    def _purge(r: TriggerResult) -> TriggerResult:
        return TriggerResult.FIRE_AND_PURGE if r.fires else r


class NeverTrigger(Trigger):
    pass


# ---------------------------------------------------------------------------
# Evictors (list-state windows only — reference EvictingWindowOperator)
# ---------------------------------------------------------------------------

class Evictor:
    def evict_before(self, elements: list, window, current_watermark: int) -> list:
        return elements

    def evict_after(self, elements: list, window, current_watermark: int) -> list:
        return elements


@dataclass
class CountEvictor(Evictor):
    max_count: int

    @staticmethod
    def of(n: int) -> "CountEvictor":
        return CountEvictor(n)

    def evict_before(self, elements, window, current_watermark):
        return elements[-self.max_count:]


@dataclass
class TimeEvictor(Evictor):
    """Keep only elements within window_max_ts - keep_time."""

    keep_time: int

    @staticmethod
    def of(keep_ms: int) -> "TimeEvictor":
        return TimeEvictor(keep_ms)

    def evict_before(self, elements, window, current_watermark):
        if not elements:
            return elements
        max_ts = max(ts for _, ts in elements)
        return [(v, ts) for v, ts in elements if ts >= max_ts - self.keep_time]
