"""Window assigners.

Analog of flink-streaming-java's assigners
(api/windowing/assigners/: TumblingEventTimeWindows, SlidingEventTimeWindows,
EventTimeSessionWindows, GlobalWindows) and of the table runtime's slice
assigners (flink-table-runtime operators/window/slicing/SliceAssigners.java).

Batch-first: every non-merging assigner can vectorize assignment over a
timestamp column (``assign_batch``) — for sliding windows this produces the
*pane/slice* index per record (one non-overlapping slice per slide period),
which is what lets the device path aggregate each record exactly once and
merge panes at fire time (the reference's slice-shared optimization,
SURVEY.md §5.7b).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional

import numpy as np

from ..core.records import MAX_TIMESTAMP

__all__ = [
    "TimeWindow", "GlobalWindow", "WindowAssigner", "TumblingEventTimeWindows",
    "TumblingProcessingTimeWindows", "SlidingEventTimeWindows",
    "SlidingProcessingTimeWindows", "CumulateWindows",
    "reject_variable_pane_assigner",
    "EventTimeSessionWindows", "ProcessingTimeSessionWindows",
    "GlobalWindows",
]


@dataclass(frozen=True, order=True)
class TimeWindow:
    """[start, end) window; max_timestamp is end-1 (reference TimeWindow)."""

    start: int
    end: int

    @property
    def max_timestamp(self) -> int:
        return self.end - 1

    def intersects(self, other: "TimeWindow") -> bool:
        return self.start <= other.max_timestamp and other.start <= self.max_timestamp

    def cover(self, other: "TimeWindow") -> "TimeWindow":
        return TimeWindow(min(self.start, other.start), max(self.end, other.end))


@dataclass(frozen=True)
class GlobalWindow:
    @property
    def max_timestamp(self) -> int:
        return MAX_TIMESTAMP


def _window_start(ts: np.ndarray, size: int, offset: int) -> np.ndarray:
    """reference TimeWindow.getWindowStartWithOffset: ts - (ts - offset) mod size
    (floor-mod, correct for negative timestamps)."""
    return ts - np.mod(ts - offset, size)


class WindowAssigner:
    is_event_time: bool = True
    is_merging: bool = False

    def assign_windows(self, timestamp: int) -> Iterable:
        raise NotImplementedError

    def assign_batch(self, timestamps: np.ndarray) -> Optional[np.ndarray]:
        """Vectorized: pane-start int64 per record, or None if not paneable."""
        return None

    @property
    def pane_size(self) -> Optional[int]:
        """Slice width in ms when the assigner decomposes into panes."""
        return None

    def windows_for_pane(self, pane_start: int) -> Iterable[TimeWindow]:
        """All windows a pane contributes to (1 for tumbling, size/slide for
        sliding) — the fire-time merge set."""
        raise NotImplementedError

    def default_trigger(self):
        from .triggers import EventTimeTrigger, ProcessingTimeTrigger
        return EventTimeTrigger() if self.is_event_time else ProcessingTimeTrigger()


@dataclass(frozen=True)
class TumblingEventTimeWindows(WindowAssigner):
    size: int
    offset: int = 0

    @staticmethod
    def of(size_ms: int, offset_ms: int = 0) -> "TumblingEventTimeWindows":
        return TumblingEventTimeWindows(size_ms, offset_ms)

    def assign_windows(self, timestamp: int):
        start = int(_window_start(np.int64(timestamp), self.size, self.offset))
        return [TimeWindow(start, start + self.size)]

    def assign_batch(self, timestamps: np.ndarray) -> np.ndarray:
        return _window_start(timestamps, self.size, self.offset)

    @property
    def pane_size(self) -> int:
        return self.size

    def windows_for_pane(self, pane_start: int):
        return [TimeWindow(pane_start, pane_start + self.size)]


class TumblingProcessingTimeWindows(TumblingEventTimeWindows):
    is_event_time = False

    @staticmethod
    def of(size_ms: int, offset_ms: int = 0) -> "TumblingProcessingTimeWindows":
        return TumblingProcessingTimeWindows(size_ms, offset_ms)


@dataclass(frozen=True)
class SlidingEventTimeWindows(WindowAssigner):
    size: int
    slide: int
    offset: int = 0

    def __post_init__(self):
        if self.size % self.slide != 0:
            # Panes require size to be a multiple of slide; reference supports
            # arbitrary size/slide via per-record multi-assign — we keep that
            # row path but lose the pane optimization.
            pass

    @staticmethod
    def of(size_ms: int, slide_ms: int,
           offset_ms: int = 0) -> "SlidingEventTimeWindows":
        return SlidingEventTimeWindows(size_ms, slide_ms, offset_ms)

    def assign_windows(self, timestamp: int):
        last_start = int(_window_start(np.int64(timestamp), self.slide, self.offset))
        out = []
        start = last_start
        while start > timestamp - self.size:
            out.append(TimeWindow(start, start + self.size))
            start -= self.slide
        return out

    def assign_batch(self, timestamps: np.ndarray) -> Optional[np.ndarray]:
        if self.size % self.slide != 0:
            return None
        return _window_start(timestamps, self.slide, self.offset)

    @property
    def pane_size(self) -> Optional[int]:
        return self.slide if self.size % self.slide == 0 else None

    def windows_for_pane(self, pane_start: int):
        n = self.size // self.slide
        return [TimeWindow(pane_start - i * self.slide,
                           pane_start - i * self.slide + self.size)
                for i in range(n)]


class SlidingProcessingTimeWindows(SlidingEventTimeWindows):
    is_event_time = False

    @staticmethod
    def of(size_ms: int, slide_ms: int,
           offset_ms: int = 0) -> "SlidingProcessingTimeWindows":
        return SlidingProcessingTimeWindows(size_ms, slide_ms, offset_ms)


def reject_variable_pane_assigner(assigner, where: str) -> None:
    """One guard for every fixed-panes-per-window fire program (device,
    mesh): cumulate windows span a VARIABLE pane count and would silently
    aggregate with sliding semantics."""
    if isinstance(assigner, CumulateWindows):
        raise ValueError(
            f"cumulate windows cannot run on the {where} window operator "
            "(variable panes per window); use the host WindowOperator "
            "(.aggregate/.sum) or the SQL CUMULATE TVF")


@dataclass(frozen=True)
class CumulateWindows(WindowAssigner):
    """Cumulative (expanding) windows (reference CUMULATE TVF /
    CumulativeWindowSpec): within each ``size`` base window, windows
    [base, base + k*step) fire at every step until the base window closes.
    Decomposes into ``step`` panes — each pane contributes to every
    expanding window of its base that ends at or after it. NOTE: windows
    span a VARIABLE number of panes (1..size/step), which the device fire
    program's fixed panes-per-window model cannot express — cumulate runs
    on the host WindowOperator (device_window.py rejects it; the planner
    routes around it)."""

    size: int
    step: int
    offset: int = 0

    def __post_init__(self):
        if self.size % self.step != 0:
            raise ValueError(
                f"CUMULATE size ({self.size}) must be a multiple of the "
                f"step ({self.step})")

    @staticmethod
    def of(size_ms: int, step_ms: int,
           offset_ms: int = 0) -> "CumulateWindows":
        return CumulateWindows(size_ms, step_ms, offset_ms)

    def _base(self, timestamp) -> int:
        return int(_window_start(np.int64(timestamp), self.size,
                                 self.offset))

    def assign_windows(self, timestamp: int):
        base = self._base(timestamp)
        n = self.size // self.step
        k_from = (timestamp - base) // self.step + 1
        return [TimeWindow(base, base + k * self.step)
                for k in range(k_from, n + 1)]

    def assign_batch(self, timestamps: np.ndarray) -> np.ndarray:
        return _window_start(timestamps, self.step, self.offset)

    @property
    def pane_size(self) -> int:
        return self.step

    def windows_for_pane(self, pane_start: int):
        base = self._base(pane_start)
        n = self.size // self.step
        k_from = (pane_start - base) // self.step + 1
        return [TimeWindow(base, base + k * self.step)
                for k in range(k_from, n + 1)]


@dataclass(frozen=True)
class EventTimeSessionWindows(WindowAssigner):
    """Merging session windows (reference EventTimeSessionWindows + the
    MergingWindowSet handled in the window operator)."""

    gap: int
    is_merging = True

    @staticmethod
    def with_gap(gap_ms: int) -> "EventTimeSessionWindows":
        return EventTimeSessionWindows(gap_ms)

    def assign_windows(self, timestamp: int):
        return [TimeWindow(timestamp, timestamp + self.gap)]


class ProcessingTimeSessionWindows(EventTimeSessionWindows):
    """Session windows on processing time (reference
    ProcessingTimeSessionWindows)."""

    is_event_time = False

    @staticmethod
    def with_gap(gap_ms: int) -> "ProcessingTimeSessionWindows":
        return ProcessingTimeSessionWindows(gap_ms)


@dataclass(frozen=True)
class GlobalWindows(WindowAssigner):
    is_event_time = False

    @staticmethod
    def create() -> "GlobalWindows":
        return GlobalWindows()

    def assign_windows(self, timestamp: int):
        return [GlobalWindow()]

    def default_trigger(self):
        from .triggers import NeverTrigger
        return NeverTrigger()
