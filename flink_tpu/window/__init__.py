"""Windowing: assigners, triggers, evictors (SURVEY.md §2.5 WindowOperator row)."""

from .assigners import (  # noqa: F401
    CumulateWindows, EventTimeSessionWindows, GlobalWindow, GlobalWindows,
    ProcessingTimeSessionWindows, SlidingEventTimeWindows,
    SlidingProcessingTimeWindows, TimeWindow,
    TumblingEventTimeWindows, TumblingProcessingTimeWindows, WindowAssigner,
)
from .triggers import (  # noqa: F401
    ContinuousEventTimeTrigger, CountEvictor, CountTrigger, EventTimeTrigger,
    Evictor, NeverTrigger, ProcessingTimeTrigger, PurgingTrigger, TimeEvictor,
    Trigger, TriggerContext, TriggerResult,
)
