"""Rule registry, finding model, suppression comments, and the
committed-baseline workflow shared by every tpu-lint rule.

Design notes
------------
* A ``Finding``'s **fingerprint** deliberately excludes the line number:
  baselined findings must survive unrelated edits that shift lines.  The
  stable identity is (rule, file, symbol, detail).
* Suppressions are inline comments: ``# lint: <tag> <reason>`` on the
  flagged line or the line above.  A tag with no reason does NOT
  suppress — the reason is the point (it is the reviewable record of
  why the exception is sound).
* Rules never import jax at module import time; Tier-B rules import it
  lazily so Tier A runs anywhere Python runs.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple


# --------------------------------------------------------------------------
# Findings


@dataclass(frozen=True)
class Finding:
    """One lint hit: where, what, and how to fix it."""

    rule: str                 # rule id, e.g. "TPU101"
    file: str                 # repo-relative posix path
    line: int                 # 1-based; 0 when the finding is file-level
    symbol: str               # stable anchor (qualname / key / site name)
    message: str
    hint: str = ""

    @property
    def fingerprint(self) -> str:
        raw = "|".join((self.rule, self.file, self.symbol))
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]

    def location(self) -> str:
        return f"{self.file}:{self.line}" if self.line else self.file

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "file": self.file, "line": self.line,
            "symbol": self.symbol, "message": self.message,
            "hint": self.hint, "fingerprint": self.fingerprint,
        }


# --------------------------------------------------------------------------
# Rules


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    tier: str                 # "A" (AST) or "B" (jaxpr)
    description: str
    fn: Callable[["AnalysisContext"], List[Finding]]


_RULES: Dict[str, Rule] = {}


def rule(id: str, title: str, tier: str, description: str):
    """Register a rule function ``fn(ctx) -> [Finding]`` under ``id``."""

    def deco(fn):
        if id in _RULES:
            raise ValueError(f"duplicate rule id {id}")
        _RULES[id] = Rule(id=id, title=title, tier=tier,
                          description=description, fn=fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    # Ensure the rule modules have been imported (registration side
    # effect) even when core is imported directly.
    from . import ast_rules, inventory, jaxpr_rules, plan_rules  # noqa: F401
    return dict(_RULES)


# --------------------------------------------------------------------------
# Settings + context


@dataclass
class AnalysisSettings:
    """Everything a rule keys off that tests may want to override (tests
    point these at a synthetic mini-package to prove each rule fires)."""

    # Tier A: host-sync rule — package-relative module paths that form
    # the device hot path (one dispatch per batch / per fire).
    hot_path_modules: Tuple[str, ...] = (
        "runtime/operators/device_window.py",
        "runtime/operators/device_session.py",
        "runtime/stream_task.py",
        "sql/device_group_agg.py",
        "parallel/sharded_window.py",
        # tiered-state residency (ISSUE 15): policy/manager/pipeline must
        # stay host-sync-free — the backend hands them plain numpy and
        # applies their decisions on device itself
        "state/tiering/policy.py",
        "state/tiering/residency.py",
        "state/tiering/prefetch.py",
    )
    # Singleton-wiring rule: deploy entry points -> (module, qualname).
    # A class entry point means "somewhere in the class's transitive
    # call graph".
    entry_points: Tuple[Tuple[str, str], ...] = (
        ("cluster/local.py", "run_job"),
        ("cluster/local.py", "deploy_local"),
        ("cluster/scheduler.py", "JobSupervisor"),
        ("cluster/distributed.py", "DistributedHost"),
    )
    # Process-global singletons every deploy path must configure.  Each
    # maps to the NAME(s) whose ``.configure(...)`` call satisfies it —
    # FLIGHT_RECORDER is an attached reporter of TRACER, so
    # TRACER.configure() wires it too (metrics/tracing.py).
    singletons: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
        ("FAULTS", ("FAULTS",)),
        ("WATCHDOG", ("WATCHDOG",)),
        ("TRACER", ("TRACER",)),
        ("FLIGHT_RECORDER", ("FLIGHT_RECORDER", "TRACER")),
        ("MESH_RUNTIME", ("MESH_RUNTIME",)),
        ("DEVICE_LEDGER", ("DEVICE_LEDGER",)),
        ("ISOLATION", ("ISOLATION",)),
    )
    # Determinism rule: span/tracing modules where time.time() is banned
    # (monotonic-anchored clock only — see now_ms() in metrics/tracing).
    span_clock_modules: Tuple[str, ...] = (
        "metrics/tracing.py",
        "metrics/device.py",
        "metrics/profiler.py",
    )
    # Determinism rule: runtime module prefixes where unseeded RNG is
    # banned (replayability of fault schedules / recovery paths).
    runtime_rng_prefixes: Tuple[str, ...] = (
        "runtime/", "cluster/", "state/", "checkpoint/", "connectors/",
    )
    # Inventory rule: extra dotted literals that are legitimate despite
    # sharing a first segment with a config-option family (watchdog
    # scopes, stall sites, ... that are not config keys).
    extra_key_vocab: Tuple[str, ...] = (
        "net.reconnect",          # StallError site for reconnect deadlines
        "checkpoint.storage",     # watchdog scope label
    )
    # Tier B: donation rule ignores programs whose total output bytes
    # are below this (tiny outputs are not worth aliasing).
    donation_min_bytes: int = 1 << 20
    # Tier B: scopes whose programs run once per FIRE (latency-critical;
    # scatter lowering there is the PR 8 regression class).  Matched as
    # substrings of the instrumented_program_cache scope.
    fire_path_scopes: Tuple[str, ...] = (
        ".fire", "pallas_topk",
    )


_TAG_RE = re.compile(r"#\s*lint:\s*([a-z0-9-]+)\s*(.*)$")


class AnalysisContext:
    """Shared state for one lint run: file set, parsed ASTs, suppression
    comments, settings.  ``package_root`` is the directory containing
    the ``flink_tpu`` package (i.e. the repo root)."""

    def __init__(self, package_root: Optional[Path] = None,
                 package_name: str = "flink_tpu",
                 settings: Optional[AnalysisSettings] = None,
                 extra_files: Sequence[str] = ("bench.py",)):
        if package_root is None:
            package_root = Path(__file__).resolve().parent.parent.parent
        self.root = Path(package_root)
        self.package_name = package_name
        self.pkg_dir = self.root / package_name
        self.settings = settings or AnalysisSettings()
        self.extra_files = tuple(extra_files)
        self._sources: Dict[str, str] = {}
        self._trees: Dict[str, ast.Module] = {}
        self._suppressions: Dict[str, Dict[int, Tuple[str, str]]] = {}

    # -- file discovery ---------------------------------------------------

    def package_files(self) -> List[str]:
        """Repo-relative posix paths of every package .py file (analysis/
        itself excluded — the linter does not lint its own rule fixtures)
        plus ``extra_files`` that exist."""
        out = []
        for p in sorted(self.pkg_dir.rglob("*.py")):
            rel = p.relative_to(self.root).as_posix()
            if rel.startswith(f"{self.package_name}/analysis/"):
                continue
            out.append(rel)
        for extra in self.extra_files:
            if (self.root / extra).is_file():
                out.append(extra)
        return out

    def pkg_rel(self, rel: str) -> str:
        """Package-relative path -> repo-relative path."""
        return f"{self.package_name}/{rel}"

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            self._sources[rel] = (self.root / rel).read_text()
        return self._sources[rel]

    def tree(self, rel: str) -> ast.Module:
        if rel not in self._trees:
            self._trees[rel] = ast.parse(self.source(rel), filename=rel)
        return self._trees[rel]

    # -- suppressions -----------------------------------------------------

    def _file_suppressions(self, rel: str) -> Dict[int, Tuple[str, str]]:
        if rel not in self._suppressions:
            table: Dict[int, Tuple[str, str]] = {}
            for i, line in enumerate(self.source(rel).splitlines(), 1):
                m = _TAG_RE.search(line)
                if m:
                    table[i] = (m.group(1), m.group(2).strip())
            self._suppressions[rel] = table
        return self._suppressions[rel]

    def suppression(self, rel: str, line: int, tag: str) -> Optional[str]:
        """Return the reason string if ``line`` (or the line above it)
        carries ``# lint: <tag> <reason>`` with a non-empty reason."""
        table = self._file_suppressions(rel)
        for ln in (line, line - 1):
            hit = table.get(ln)
            if hit and hit[0] == tag and hit[1]:
                return hit[1]
        return None


# --------------------------------------------------------------------------
# Running + baseline


def run_rules(ctx: AnalysisContext,
              rule_ids: Optional[Iterable[str]] = None,
              skipped: Optional[List[str]] = None) -> List[Finding]:
    """Run the selected rules (all by default) and return findings sorted
    by (file, line, rule).  Unknown rule ids raise ValueError (the CLI
    maps that to exit code 2)."""
    rules = all_rules()
    if rule_ids is None:
        selected = list(rules.values())
    else:
        ids = list(rule_ids)
        unknown = [r for r in ids if r not in rules]
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(unknown)}")
        selected = [rules[r] for r in ids]
    findings: List[Finding] = []
    for r in selected:
        try:
            findings.extend(r.fn(ctx))
        except _RuleSkipped as e:
            if skipped is not None:
                skipped.append(f"{r.id}: {e}")
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.symbol))
    return findings


class _RuleSkipped(Exception):
    """Raised by a rule that cannot run in this environment (e.g. Tier B
    without jax).  Reported as skipped, never as clean-by-accident when
    the caller asked to see skips."""


def skip_rule(reason: str) -> None:
    raise _RuleSkipped(reason)


def baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Optional[Path] = None) -> List[dict]:
    path = path or baseline_path()
    if not path.is_file():
        return []
    data = json.loads(path.read_text())
    return list(data.get("entries", []))


def save_baseline(findings: Sequence[Finding],
                  path: Optional[Path] = None,
                  previous: Optional[List[dict]] = None,
                  default_reason: Optional[str] = None) -> None:
    """Write the baseline for ``findings``; reasons from a previous
    baseline are preserved by fingerprint, new entries get
    ``default_reason`` (the CLI's ``--reason``) or a TODO reason that a
    reviewer must replace (the committed baseline holds only justified
    exceptions — BASE601 flags entries still carrying the TODO)."""
    path = path or baseline_path()
    prev = {e["fingerprint"]: e for e in (previous
                                          if previous is not None
                                          else load_baseline(path))}
    entries = []
    for f in findings:
        old = prev.get(f.fingerprint)
        entries.append({
            "rule": f.rule, "file": f.file, "symbol": f.symbol,
            "fingerprint": f.fingerprint,
            "reason": (old or {}).get(
                "reason",
                default_reason or "TODO: justify this exception or fix it"),
        })
    path.write_text(json.dumps({"version": 1, "entries": entries},
                               indent=2, sort_keys=True) + "\n")


def diff_against_baseline(
        findings: Sequence[Finding],
        baseline: Optional[List[dict]] = None,
) -> Tuple[List[Finding], List[dict]]:
    """Split findings into (unbaselined, stale_baseline_entries).  Stale
    entries — baselined findings that no longer occur — are reported so
    the baseline shrinks as fixes land instead of rotting."""
    if baseline is None:
        baseline = load_baseline()
    known = {e["fingerprint"] for e in baseline}
    seen = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in known]
    stale = [e for e in baseline if e["fingerprint"] not in seen]
    return new, stale
