"""Tier-A inventory-drift rules (TPU3xx): one mechanism locking code
literals <-> declared inventories <-> committed docs, generalizing the
three ad-hoc doc-lock tests this framework replaced (span inventory in
tests/test_tracing.py, fault-site and config-docs locks in
tests/test_core.py).

Imports here touch only numpy-level package modules (metrics.tracing,
runtime.faults, core.config, docs) — never jax.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import AnalysisContext, Finding, rule

# --------------------------------------------------------------------------
# TPU301 — span inventory: code spans == SPAN_INVENTORY == OBSERVABILITY.md

_SPAN_CALL_RE = re.compile(r'\.span\(\s*"(\w+)",\s*"(\w+)"')
_SPAN_DOC_ROW = re.compile(r"^\| `(\w+)` \| `(\w+)` \|")


def _load_span_inventory(ctx: AnalysisContext):
    from flink_tpu.metrics.tracing import SPAN_INVENTORY
    return SPAN_INVENTORY


@rule("TPU301", "span inventory drift", "A",
      "every TRACER.span(scope, name) literal must appear in "
      "SPAN_INVENTORY (metrics/tracing.py) and in the span table of "
      "docs/OBSERVABILITY.md, and vice versa — the inventory is the "
      "contract consumers filter traces by")
def span_inventory_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    inv_rel = ctx.pkg_rel("metrics/tracing.py")
    inventory = _load_span_inventory(ctx)
    inv_pairs = {(scope, name) for scope, name, _where in inventory}

    code_pairs: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for rel in ctx.package_files():
        if not rel.startswith(f"{ctx.package_name}/"):
            continue
        for i, line in enumerate(ctx.source(rel).splitlines(), 1):
            for m in _SPAN_CALL_RE.finditer(line):
                code_pairs.setdefault((m.group(1), m.group(2)), (rel, i))

    doc_rel = "docs/OBSERVABILITY.md"
    doc_pairs: Set[Tuple[str, str]] = set()
    doc_path = ctx.root / doc_rel
    if doc_path.is_file():
        for line in doc_path.read_text().splitlines():
            m = _SPAN_DOC_ROW.match(line)
            if m:
                doc_pairs.add((m.group(1), m.group(2)))
    else:
        findings.append(Finding(
            rule="TPU301", file=doc_rel, line=0, symbol=doc_rel,
            message="docs/OBSERVABILITY.md missing", hint="restore it"))

    for pair, (rel, line) in sorted(code_pairs.items()):
        if pair not in inv_pairs:
            findings.append(Finding(
                rule="TPU301", file=rel, line=line,
                symbol=f"code-not-inventoried:{pair[0]}.{pair[1]}",
                message=f"span ({pair[0]}, {pair[1]}) emitted here but "
                        "missing from SPAN_INVENTORY",
                hint="add it to SPAN_INVENTORY in metrics/tracing.py "
                     "and to the docs/OBSERVABILITY.md table"))
    for scope, name, where in inventory:
        if (scope, name) not in code_pairs:
            findings.append(Finding(
                rule="TPU301", file=inv_rel, line=0,
                symbol=f"inventoried-not-in-code:{scope}.{name}",
                message=f"SPAN_INVENTORY lists ({scope}, {name}) but no "
                        "code emits it",
                hint="delete the stale inventory row (and its docs row)"))
        for cited in re.findall(r"[\w/]+\.py", where):
            if not (ctx.root / ctx.package_name / cited).is_file():
                findings.append(Finding(
                    rule="TPU301", file=inv_rel, line=0,
                    symbol=f"stale-citation:{scope}.{name}:{cited}",
                    message=f"SPAN_INVENTORY cites {cited} but "
                            f"{ctx.package_name}/{cited} does not exist",
                    hint="fix the 'where' citation"))
    if doc_pairs:
        for pair in sorted(inv_pairs - doc_pairs):
            findings.append(Finding(
                rule="TPU301", file=doc_rel, line=0,
                symbol=f"doc-missing:{pair[0]}.{pair[1]}",
                message=f"span ({pair[0]}, {pair[1]}) is inventoried but "
                        "missing from the docs/OBSERVABILITY.md table",
                hint="add the table row"))
        for pair in sorted(doc_pairs - inv_pairs):
            findings.append(Finding(
                rule="TPU301", file=doc_rel, line=0,
                symbol=f"doc-stale:{pair[0]}.{pair[1]}",
                message=f"docs/OBSERVABILITY.md lists span "
                        f"({pair[0]}, {pair[1]}) that is not inventoried",
                hint="delete the stale table row"))
    if list(inventory) != sorted(inventory):
        findings.append(Finding(
            rule="TPU301", file=inv_rel, line=0, symbol="unsorted",
            message="SPAN_INVENTORY is not sorted (scope, name)",
            hint="keep it sorted so diffs stay reviewable"))
    return findings


# --------------------------------------------------------------------------
# TPU302 — fault-site inventory: FAULT_SITES == code literals == docs

_SITE_DOC_ROW = re.compile(r"^\| `([a-z0-9_.-]+)` \|")


def _load_fault_sites(ctx: AnalysisContext):
    from flink_tpu.runtime.faults import FAULT_SITES
    return FAULT_SITES


@rule("TPU302", "fault-site inventory drift", "A",
      "every FAULTS.fire/check site literal must be a declared "
      "FAULT_SITES member, every declared site must be threaded "
      "somewhere in code, and the docs/ROBUSTNESS.md fault-site table "
      "must list exactly the declared sites")
def fault_site_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    sites_rel = ctx.pkg_rel("runtime/faults.py")
    declared = tuple(_load_fault_sites(ctx))
    declared_set = set(declared)

    fire_re = re.compile(
        r'(?:FAULTS\.(?:fire|check)|fire_with_retries)\(\s*"([^"]+)"')
    used: Dict[str, Tuple[str, int]] = {}
    literals: Set[str] = set()
    for rel in ctx.package_files():
        src = ctx.source(rel)
        for i, line in enumerate(src.splitlines(), 1):
            for m in fire_re.finditer(line):
                used.setdefault(m.group(1), (rel, i))
        for m in re.finditer(r'"([a-z0-9_.-]+)"', src):
            literals.add(m.group(1))

    for site, (rel, line) in sorted(used.items()):
        if site not in declared_set:
            findings.append(Finding(
                rule="TPU302", file=rel, line=line,
                symbol=f"undeclared-site:{site}",
                message=f"fault site '{site}' fired here but not in "
                        "FAULT_SITES (FaultRule.parse would reject a "
                        "rule targeting it)",
                hint="add it to FAULT_SITES in runtime/faults.py and to "
                     "the docs/ROBUSTNESS.md table"))
    for site in declared:
        if site not in literals:
            findings.append(Finding(
                rule="TPU302", file=sites_rel, line=0,
                symbol=f"unthreaded-site:{site}",
                message=f"FAULT_SITES declares '{site}' but no code "
                        "references it",
                hint="thread the site or delete the declaration"))

    doc_rel = "docs/ROBUSTNESS.md"
    doc_path = ctx.root / doc_rel
    if doc_path.is_file():
        text = doc_path.read_text()
        section = text.split("## Fault sites", 1)
        doc_sites: Set[str] = set()
        if len(section) == 2:
            for line in section[1].split("\n## ", 1)[0].splitlines():
                m = _SITE_DOC_ROW.match(line)
                if m and m.group(1) != "Site":
                    doc_sites.add(m.group(1))
        for site in sorted(declared_set - doc_sites):
            findings.append(Finding(
                rule="TPU302", file=doc_rel, line=0,
                symbol=f"doc-missing:{site}",
                message=f"fault site '{site}' missing from the "
                        "docs/ROBUSTNESS.md fault-site table",
                hint="add the table row"))
        for site in sorted(doc_sites - declared_set):
            findings.append(Finding(
                rule="TPU302", file=doc_rel, line=0,
                symbol=f"doc-stale:{site}",
                message=f"docs/ROBUSTNESS.md lists fault site '{site}' "
                        "that FAULT_SITES does not declare",
                hint="delete the stale table row"))
    else:
        findings.append(Finding(
            rule="TPU302", file=doc_rel, line=0, symbol=doc_rel,
            message="docs/ROBUSTNESS.md missing", hint="restore it"))
    return findings


# --------------------------------------------------------------------------
# TPU303 — committed config docs must be freshly generated


@rule("TPU303", "config docs stale", "A",
      "docs/CONFIG.md is generated from the option registry "
      "(flink_tpu.docs.generate_config_docs); a hand-edit or an option "
      "added without regenerating makes the committed docs lie")
def config_docs_rule(ctx: AnalysisContext) -> List[Finding]:
    from flink_tpu.core.config import all_options
    from flink_tpu.docs import generate_config_docs
    findings: List[Finding] = []
    doc_rel = "docs/CONFIG.md"
    expected = generate_config_docs()
    for key in all_options():
        n = expected.count(f"| `{key}` |")
        if n != 1:
            findings.append(Finding(
                rule="TPU303", file=doc_rel, line=0,
                symbol=f"coverage:{key}",
                message=f"option {key} has {n} table rows in the "
                        "generated docs (want exactly 1)",
                hint="fix the *Options class docs grouping"))
    doc_path = ctx.root / doc_rel
    if not doc_path.is_file() or doc_path.read_text() != expected:
        findings.append(Finding(
            rule="TPU303", file=doc_rel, line=0, symbol="stale",
            message="docs/CONFIG.md does not match "
                    "generate_config_docs() output",
            hint="python -c \"from flink_tpu.docs import write_config_docs;"
                 " write_config_docs()\""))
    return findings


# --------------------------------------------------------------------------
# TPU305 — ledger-site inventory: code literals == LEDGER_SITE_INVENTORY
# == the "### Ledger sites" table of docs/OBSERVABILITY.md

# Sites appear either as an instrumented-cache builder scope or as a
# direct ledger record; both calls wrap arguments, so these run against
# the whole source (\s* crosses the line break after the open paren).
_LEDGER_SITE_RE = re.compile(
    r'(?:instrumented_program_cache|DEVICE_LEDGER\.record)\(\s*'
    r'"([a-z0-9_.]+)"')
_LEDGER_DOC_ROW = re.compile(r"^\| `([a-z0-9_.]+)` \|")


def _load_ledger_inventory(ctx: AnalysisContext):
    from flink_tpu.metrics.profiler import LEDGER_SITE_INVENTORY
    return LEDGER_SITE_INVENTORY


@rule("TPU305", "ledger-site inventory drift", "A",
      "every instrumented_program_cache scope / DEVICE_LEDGER.record "
      "site literal must appear in LEDGER_SITE_INVENTORY "
      "(metrics/profiler.py) and in the ledger-site table of "
      "docs/OBSERVABILITY.md, and vice versa — the inventory is the "
      "contract profile consumers attribute device time by")
def ledger_site_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    inv_rel = ctx.pkg_rel("metrics/profiler.py")
    inventory = _load_ledger_inventory(ctx)
    inv_sites = {site for site, _where in inventory}

    code_sites: Dict[str, Tuple[str, int]] = {}
    for rel in ctx.package_files():
        src = ctx.source(rel)
        for m in _LEDGER_SITE_RE.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            code_sites.setdefault(m.group(1), (rel, line))

    doc_rel = "docs/OBSERVABILITY.md"
    doc_sites: Set[str] = set()
    doc_path = ctx.root / doc_rel
    if doc_path.is_file():
        section = doc_path.read_text().split("### Ledger sites", 1)
        if len(section) == 2:
            for line in section[1].split("\n#", 1)[0].splitlines():
                m = _LEDGER_DOC_ROW.match(line)
                if m:
                    doc_sites.add(m.group(1))
    else:
        findings.append(Finding(
            rule="TPU305", file=doc_rel, line=0, symbol=doc_rel,
            message="docs/OBSERVABILITY.md missing", hint="restore it"))

    for site, (rel, line) in sorted(code_sites.items()):
        if site not in inv_sites:
            findings.append(Finding(
                rule="TPU305", file=rel, line=line,
                symbol=f"code-not-inventoried:{site}",
                message=f"ledger site '{site}' recorded here but missing "
                        "from LEDGER_SITE_INVENTORY",
                hint="add it to LEDGER_SITE_INVENTORY in "
                     "metrics/profiler.py and to the docs/OBSERVABILITY.md "
                     "ledger-site table"))
    for site, where in inventory:
        if site not in code_sites:
            findings.append(Finding(
                rule="TPU305", file=inv_rel, line=0,
                symbol=f"inventoried-not-in-code:{site}",
                message=f"LEDGER_SITE_INVENTORY lists '{site}' but no "
                        "code records it",
                hint="delete the stale inventory row (and its docs row)"))
        for cited in re.findall(r"[\w/]+\.py", where):
            if not (ctx.root / ctx.package_name / cited).is_file():
                findings.append(Finding(
                    rule="TPU305", file=inv_rel, line=0,
                    symbol=f"stale-citation:{site}:{cited}",
                    message=f"LEDGER_SITE_INVENTORY cites {cited} but "
                            f"{ctx.package_name}/{cited} does not exist",
                    hint="fix the 'where' citation"))
    if doc_path.is_file():
        if not doc_sites:
            findings.append(Finding(
                rule="TPU305", file=doc_rel, line=0,
                symbol="section-missing",
                message="docs/OBSERVABILITY.md has no '### Ledger sites' "
                        "table",
                hint="add the section (see LEDGER_SITE_INVENTORY)"))
        else:
            for site in sorted(inv_sites - doc_sites):
                findings.append(Finding(
                    rule="TPU305", file=doc_rel, line=0,
                    symbol=f"doc-missing:{site}",
                    message=f"ledger site '{site}' is inventoried but "
                            "missing from the docs/OBSERVABILITY.md "
                            "ledger-site table",
                    hint="add the table row"))
            for site in sorted(doc_sites - inv_sites):
                findings.append(Finding(
                    rule="TPU305", file=doc_rel, line=0,
                    symbol=f"doc-stale:{site}",
                    message=f"docs/OBSERVABILITY.md lists ledger site "
                            f"'{site}' that is not inventoried",
                    hint="delete the stale table row"))
    if list(inventory) != sorted(inventory):
        findings.append(Finding(
            rule="TPU305", file=inv_rel, line=0, symbol="unsorted",
            message="LEDGER_SITE_INVENTORY is not sorted by site",
            hint="keep it sorted so diffs stay reviewable"))
    return findings


# --------------------------------------------------------------------------
# TPU304 — config-key literals must resolve to declared options

_KEYISH_RE = re.compile(r"^[a-z][a-z0-9-]*(\.[a-z0-9-]+)+$")
_SITEISH_KWARGS = {"scope", "site"}


def _config_vocab(ctx: AnalysisContext) -> Tuple[Set[str], Set[str]]:
    from flink_tpu.core.config import all_options
    from flink_tpu.runtime.faults import FAULT_SITES
    keys = set(all_options())
    vocab = set(keys) | set(FAULT_SITES) | set(ctx.settings.extra_key_vocab)
    families = {k.split(".")[0] for k in keys}
    return vocab, families


def _exempt_constants(tree: ast.Module) -> Set[int]:
    """ids of string Constant nodes used as watchdog/fault SITE labels
    (scope=/site= kwargs or first arg of run/fire/check/deadline_for/
    stall_bounded/fire_with_retries) — sites are an open namespace, not
    config keys."""
    exempt: Set[int] = set()
    site_fns = {"run", "fire", "check", "fire_with_retries",
                "stall_bounded", "deadline_for", "trip", "StallError",
                "note_stall", "note_verify_failure",
                "note_restore_fallback",
                # program-cache scopes ("mesh.step", ...) are an open
                # namespace keyed off the builder, not config keys
                "instrumented_program_cache"}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg in _SITEISH_KWARGS and isinstance(kw.value,
                                                        ast.Constant):
                exempt.add(id(kw.value))
        fname = None
        f = node.func
        if isinstance(f, ast.Attribute):
            fname = f.attr
        elif isinstance(f, ast.Name):
            fname = f.id
        if fname in site_fns and node.args and \
                isinstance(node.args[0], ast.Constant):
            exempt.add(id(node.args[0]))
    return exempt


@rule("TPU304", "config-key literal not declared", "A",
      "a dotted literal whose first segment matches a config-option "
      "family but that is not a declared key is a typo waiting to "
      "silently fall back to defaults (config.set/get never validates "
      "free-form keys)")
def config_key_literal_rule(ctx: AnalysisContext) -> List[Finding]:
    vocab, families = _config_vocab(ctx)
    findings: List[Finding] = []
    for rel in ctx.package_files():
        try:
            tree = ctx.tree(rel)
        except (FileNotFoundError, SyntaxError):
            continue
        exempt = _exempt_constants(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            val = node.value
            if not _KEYISH_RE.match(val):
                continue
            if val.split(".")[0] not in families:
                continue
            if val in vocab or id(node) in exempt:
                continue
            # prefix strings used for startswith()-style family matches
            if any(k.startswith(val + ".") or k == val for k in vocab):
                continue
            if ctx.suppression(rel, node.lineno, "key-ok"):
                continue
            findings.append(Finding(
                rule="TPU304", file=rel, line=node.lineno,
                symbol=f"key:{val}",
                message=f"'{val}' looks like a config key (family "
                        f"'{val.split('.')[0]}') but no such option is "
                        "declared in core/config.py",
                hint="fix the typo, declare the option, or annotate "
                     "'# lint: key-ok <reason>' if it is not a config "
                     "key"))
    return findings
