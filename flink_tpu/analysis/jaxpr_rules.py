"""Tier-B jaxpr program audit (JX5xx): abstractly re-trace every
compiled-segment builder that registered through
``instrumented_program_cache`` (metrics/device.py PROGRAM_AUDIT) and
lint the program IR itself.

The audit needs a populated registry: either a pipeline already ran in
this process (bench.py --audit) or ``exercise_programs()`` runs a tiny
Q5-shaped job first (the cli lint path).  Without jax the rules report
themselves as skipped — Tier A never depends on them.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .core import AnalysisContext, Finding, rule, skip_rule

# --------------------------------------------------------------------------
# Registry access + shared tracing helpers


def _entries():
    try:
        from flink_tpu.metrics.device import PROGRAM_AUDIT
    except Exception as e:  # pragma: no cover - import failure only
        skip_rule(f"metrics.device unavailable: {e}")
    if not PROGRAM_AUDIT:
        skip_rule("no programs registered — run exercise_programs() or a "
                  "pipeline first")
    return list(PROGRAM_AUDIT)


def _require_jax():
    try:
        import jax  # noqa: F401
        return jax
    except Exception as e:
        skip_rule(f"jax unavailable: {e}")


def _entry_location(ctx: AnalysisContext, entry) -> Tuple[str, int]:
    if entry.source:
        fname, lineno = entry.source
        try:
            from pathlib import Path
            rel = Path(fname).resolve().relative_to(ctx.root.resolve())
            return rel.as_posix(), lineno
        except ValueError:
            return fname, lineno
    return f"program:{entry.scope}", 0


def _trace_jaxpr(jax, entry):
    """ClosedJaxpr of the program at its recorded abstract signature, or
    None when the program cannot be abstractly re-traced (e.g. it closes
    over concrete device buffers)."""
    try:
        return jax.make_jaxpr(entry.fn)(*entry.abstract_args,
                                        **entry.abstract_kwargs)
    except Exception:
        return None


def _iter_eqns(jaxpr):
    """All equations, recursing into nested (pjit / scan / cond / …)
    sub-jaxprs via eqn params."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                yield from _iter_eqns(sub)


def _sub_jaxprs(val):
    inner = getattr(val, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        yield inner
    elif hasattr(val, "eqns"):
        yield val
    elif isinstance(val, (tuple, list)):
        for v in val:
            yield from _sub_jaxprs(v)


def _all_avals(jaxpr):
    seen = []

    def collect(j):
        for v in list(j.invars) + list(j.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                seen.append(aval)
        for eqn in j.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(v, "aval", None)
                if aval is not None:
                    seen.append(aval)
            for val in eqn.params.values():
                for sub in _sub_jaxprs(val):
                    collect(sub)

    collect(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return seen


# --------------------------------------------------------------------------
# JX501 — scatter lowering in a fire-path program


@rule("JX501", "scatter lowering on the fire path", "B",
      "scatter/scatter-add primitives lower to a serial loop on the CPU "
      "fallback rung and a slow DUS cascade on TPU; per-fire programs "
      "(latency-critical, once per pane) must stay scatter-free — the "
      "PR 8 top-k regression class")
def scatter_rule(ctx: AnalysisContext) -> List[Finding]:
    jax = _require_jax()
    findings: List[Finding] = []
    for entry in _entries():
        if not any(tok in entry.scope
                   for tok in ctx.settings.fire_path_scopes):
            continue
        closed = _trace_jaxpr(jax, entry)
        if closed is None:
            continue
        prims = sorted({eqn.primitive.name
                        for eqn in _iter_eqns(closed.jaxpr)
                        if eqn.primitive.name.startswith("scatter")})
        if not prims:
            continue
        file, line = _entry_location(ctx, entry)
        findings.append(Finding(
            rule="JX501", file=file, line=line,
            symbol=f"{entry.scope}:{'+'.join(prims)}",
            message=f"fire-path program '{entry.scope}' lowers "
                    f"{', '.join(prims)}",
            hint="rank/permute with sort- or bisection-based selection "
                 "(ops/topk.py masked_topk_bisect) instead of scatter; "
                 "if the scatter is provably amortized, baseline the "
                 "finding with a reason"))
    return findings


# --------------------------------------------------------------------------
# JX502 — float64 leak


@rule("JX502", "float64 leak in a compiled segment", "B",
      "f64 halves vector throughput on TPU (and silently doubles "
      "buffer bytes); device programs are int/f32 by contract — an f64 "
      "aval usually means a Python float or np.float64 leaked into the "
      "trace")
def f64_rule(ctx: AnalysisContext) -> List[Finding]:
    jax = _require_jax()
    import numpy as np
    findings: List[Finding] = []
    for entry in _entries():
        closed = _trace_jaxpr(jax, entry)
        if closed is None:
            continue
        hit = sorted({str(getattr(a, "dtype", ""))
                      for a in _all_avals(closed)
                      if getattr(a, "dtype", None) == np.float64})
        if not hit:
            continue
        file, line = _entry_location(ctx, entry)
        findings.append(Finding(
            rule="JX502", file=file, line=line,
            symbol=f"{entry.scope}:float64",
            message=f"program '{entry.scope}' carries float64 values",
            hint="pin the accumulator dtype (jnp.float32 / int64) at "
                 "the leak site; if f64 is required for exactness, "
                 "baseline with a reason"))
    return findings


# --------------------------------------------------------------------------
# JX503 — large outputs without donation aliasing


def _aval_bytes(aval) -> int:
    try:
        import numpy as np
        n = 1
        for d in aval.shape:
            n *= int(d)
        return n * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


@rule("JX503", "large output buffer without donation", "B",
      "a program whose outputs are large and shape-match an input "
      "should donate (donate_argnums) so XLA reuses the input buffer "
      "in place of a fresh HBM allocation per dispatch")
def donation_rule(ctx: AnalysisContext) -> List[Finding]:
    jax = _require_jax()
    findings: List[Finding] = []
    for entry in _entries():
        lower = getattr(entry.fn, "lower", None)
        if lower is None:
            continue
        try:
            lowered = lower(*entry.abstract_args, **entry.abstract_kwargs)
            text = lowered.as_text()
        except Exception:
            continue
        # donation shows as input_output_alias once compiled, or as the
        # tf.aliasing_output arg attribute in StableHLO (what lower()
        # emits on the CPU rung, where XLA ignores the donation but the
        # intent is still declared)
        if "input_output_alias" in text or "aliasing_output" in text:
            continue
        closed = _trace_jaxpr(jax, entry)
        if closed is None:
            continue
        out_avals = [getattr(v, "aval", None)
                     for v in closed.jaxpr.outvars]
        out_bytes = sum(_aval_bytes(a) for a in out_avals if a is not None)
        if out_bytes < ctx.settings.donation_min_bytes:
            continue
        in_sigs = {(tuple(a.shape), str(a.dtype))
                   for a in (getattr(v, "aval", None)
                             for v in closed.jaxpr.invars)
                   if a is not None and getattr(a, "shape", None)
                   is not None}
        matched = any(
            a is not None and getattr(a, "shape", None) is not None
            and (tuple(a.shape), str(a.dtype)) in in_sigs
            for a in out_avals)
        if not matched:
            continue
        file, line = _entry_location(ctx, entry)
        findings.append(Finding(
            rule="JX503", file=file, line=line,
            symbol=f"{entry.scope}:no-donation",
            message=f"program '{entry.scope}' returns "
                    f"{out_bytes >> 20} MiB with a shape-matched input "
                    "but no input_output_alias",
            hint="add donate_argnums for the state buffers the program "
                 "consumes-and-replaces; baseline with a reason if the "
                 "input must stay live"))
    return findings


# --------------------------------------------------------------------------
# JX504 — value-derived cache keys (recompile hazard)


def _array_signature(jax, entry) -> str:
    """Shape/dtype-only signature of the recorded dispatch: non-array
    leaves are EXCLUDED so that two builds differing only in a scalar
    value (or in builder args) but identical in buffer shapes collide —
    which is exactly the recompile hazard."""
    leaves = jax.tree_util.tree_leaves((entry.abstract_args,
                                        entry.abstract_kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
    return repr(sig)


@rule("JX504", "cache key derived from values, not shapes", "B",
      "two builds of the same scope with identical buffer shapes/dtypes "
      "mean the builder's cache key varies with a VALUE — every new "
      "value pays a fresh compile (tens of seconds behind a tunnel) "
      "instead of a cache hit; recompiles==0 in steady state is the "
      "core perf contract")
def recompile_hazard_rule(ctx: AnalysisContext) -> List[Finding]:
    jax = _require_jax()
    findings: List[Finding] = []
    by_scope_sig: Dict[Tuple[str, str], list] = {}
    for entry in _entries():
        by_scope_sig.setdefault(
            (entry.scope, _array_signature(jax, entry)), []).append(entry)
    for (scope, _sig), group in sorted(by_scope_sig.items()):
        keys = {e.build_key for e in group}
        if len(group) < 2 or len(keys) < 2:
            continue
        file, line = _entry_location(ctx, group[0])
        findings.append(Finding(
            rule="JX504", file=file, line=line,
            symbol=f"{scope}:value-keyed",
            message=f"scope '{scope}' compiled {len(group)} programs "
                    "with identical array shapes/dtypes but different "
                    "builder keys — the cache key depends on values",
            hint="key the builder on shape/dtype/config only; pass "
                 "per-batch values as traced arguments"))
    return findings


# --------------------------------------------------------------------------
# JX505 — sharded (mesh) programs must be keyed by LOCAL shard shapes


_MESH_SCOPE_PREFIX = "mesh."
# repr((args, kwargs)) of a builder whose first argument is the canonical
# local_signature tuple — see parallel/sharded_window.local_signature
_LOCAL_KEY_PREFIX = "((('local',"


@rule("JX505", "sharded program keyed by non-local shapes", "B",
      "every 'mesh.*' program builder must be keyed by the local-shard "
      "signature (parallel/sharded_window.local_signature: schema + "
      "per-device dims) and NEVER by the device count or a global "
      "[D, ...] shape — a global-keyed builder compiles a different "
      "program per mesh size, so a live rescale that preserves local "
      "shard shapes pays a recompile instead of a cache hit "
      "(recompiles==0 across rescale is the PR 12 contract)")
def mesh_local_key_rule(ctx: AnalysisContext) -> List[Finding]:
    jax = _require_jax()
    entries = [e for e in _entries()
               if e.scope.startswith(_MESH_SCOPE_PREFIX)]
    if not entries:
        skip_rule("no 'mesh.*' programs registered — run a sharded "
                  "pipeline or exercise_programs() first")
    findings: List[Finding] = []
    for entry in entries:
        file, line = _entry_location(ctx, entry)
        if not entry.build_key.startswith(_LOCAL_KEY_PREFIX):
            findings.append(Finding(
                rule="JX505", file=file, line=line,
                symbol=f"{entry.scope}:not-local-keyed",
                message=f"mesh program '{entry.scope}' build key "
                        f"{entry.build_key[:80]!r} is not derived from "
                        "local_signature (missing the 'local' marker as "
                        "its first builder argument)",
                hint="key the builder on local_signature(aggs, capacity, "
                     "ring) + static config; bind the concrete Mesh "
                     "inside the cache entry (see _step_program)"))
            continue
        # a global dispatch shape leaking into the key: any [D, ...] aval
        # of the recorded dispatch appearing verbatim means the key varies
        # with the mesh size (local keys carry dims, never shape tuples)
        leaked = set()
        for leaf in jax.tree_util.tree_leaves((entry.abstract_args,
                                               entry.abstract_kwargs)):
            shape = getattr(leaf, "shape", None)
            if (shape is not None and getattr(leaf, "dtype", None)
                    is not None and len(shape) >= 2):
                if repr(tuple(int(d) for d in shape)) in entry.build_key:
                    leaked.add(tuple(int(d) for d in shape))
        if leaked:
            findings.append(Finding(
                rule="JX505", file=file, line=line,
                symbol=f"{entry.scope}:global-shape-keyed",
                message=f"mesh program '{entry.scope}' build key embeds "
                        f"global dispatch shape(s) "
                        f"{sorted(leaked)} — the key varies with the "
                        "device count",
                hint="derive the key from per-device shard dims only; "
                     "global [D, ...] shapes belong to the traced "
                     "arguments, not the cache key"))
    return findings


# --------------------------------------------------------------------------
# JX6xx — fused-chain program audit (the fusion certifier's runtime half:
# graph/fusion.py certifies the plan, these rules lock the programs the
# lowering actually built; scopes are "chain.fused_prelude" — the
# source-decode + pure stages — and "chain.fused_step" — prelude + the
# donated window step, the one dispatch per micro-batch)


_CHAIN_PRELUDE_SCOPE = "chain.fused_prelude"
_CHAIN_STEP_SCOPE = "chain.fused_step"


def _chain_entries(prefix: str):
    entries = [e for e in _entries() if e.scope.startswith(prefix)]
    if not entries:
        skip_rule(f"no '{prefix}' programs registered — run a fused "
                  "pipeline (pipeline.fusion.enabled) first")
    return entries


@rule("JX601", "fused chain prelude must be scatter-free", "B",
      "the certified source-decode -> filter/map stages of a fused "
      "chain run once per micro-batch ahead of the window step; a "
      "scatter there lowers to a serial loop on the CPU rung and "
      "forfeits the fusion win (the window fold's own scatters are "
      "governed separately by the fire-path rule)")
def chain_scatter_rule(ctx: AnalysisContext) -> List[Finding]:
    jax = _require_jax()
    findings: List[Finding] = []
    for entry in _chain_entries(_CHAIN_PRELUDE_SCOPE):
        closed = _trace_jaxpr(jax, entry)
        if closed is None:
            continue
        prims = sorted({eqn.primitive.name
                        for eqn in _iter_eqns(closed.jaxpr)
                        if eqn.primitive.name.startswith("scatter")})
        if not prims:
            continue
        file, line = _entry_location(ctx, entry)
        findings.append(Finding(
            rule="JX601", file=file, line=line,
            symbol=f"{entry.scope}:{'+'.join(prims)}",
            message=f"fused chain prelude '{entry.scope}' lowers "
                    f"{', '.join(prims)}",
            hint="express the stage with gathers/masks/segment ops; a "
                 "stage that genuinely needs scatter is not certifiable "
                 "as part of the prelude"))
    return findings


@rule("JX602", "donation must thread through the fused chain", "B",
      "the fused step consumes-and-replaces the window state planes; "
      "without input_output_alias every micro-batch allocates a fresh "
      "copy of the whole table, so donation is mandatory for chain "
      "step programs regardless of size")
def chain_donation_rule(ctx: AnalysisContext) -> List[Finding]:
    _require_jax()
    findings: List[Finding] = []
    for entry in _chain_entries(_CHAIN_STEP_SCOPE):
        lower = getattr(entry.fn, "lower", None)
        text = ""
        if lower is not None:
            try:
                text = lower(*entry.abstract_args,
                             **entry.abstract_kwargs).as_text()
            except Exception:
                continue
        if "input_output_alias" in text or "aliasing_output" in text:
            continue
        file, line = _entry_location(ctx, entry)
        findings.append(Finding(
            rule="JX602", file=file, line=line,
            symbol=f"{entry.scope}:no-donation",
            message=f"fused chain step '{entry.scope}' declares no "
                    "buffer donation: state planes are copied every "
                    "micro-batch",
            hint="thread donate_argnums through the composed program for "
                 "the table and every accumulator plane"))
    return findings


@rule("JX603", "fused chain cache key must be shape-only", "B",
      "a fused chain program is rebuilt per (shapes, dtypes) bucket "
      "only; any value or identity (closure id, start index, batch "
      "number) in the cache key means a recompile per micro-batch — "
      "the exact failure the certifier exists to prevent")
def chain_cache_key_rule(ctx: AnalysisContext) -> List[Finding]:
    jax = _require_jax()
    findings: List[Finding] = []
    entries = _chain_entries("chain.")
    for entry in entries:
        expected = _array_signature(jax, entry)
        if entry.build_key == expected:
            continue
        file, line = _entry_location(ctx, entry)
        findings.append(Finding(
            rule="JX603", file=file, line=line,
            symbol=f"{entry.scope}:value-keyed",
            message=f"chain program '{entry.scope}' build key "
                    f"{entry.build_key!r} is not the canonical "
                    "shape/dtype signature of its dispatch",
            hint="derive the key with runtime.compiled.shape_key(...) "
                 "from the traced arguments only"))
    by_scope_sig: Dict[Tuple[str, str], list] = {}
    for entry in entries:
        by_scope_sig.setdefault(
            (entry.scope, _array_signature(jax, entry)), []).append(entry)
    for (scope, _sig), group in sorted(by_scope_sig.items()):
        if len(group) < 2 or len({e.build_key for e in group}) < 2:
            continue
        file, line = _entry_location(ctx, group[0])
        findings.append(Finding(
            rule="JX603", file=file, line=line,
            symbol=f"{scope}:key-collision",
            message=f"chain scope '{scope}' compiled {len(group)} "
                    "programs with identical array signatures but "
                    "different build keys",
            hint="derive the key with runtime.compiled.shape_key(...) "
                 "from the traced arguments only"))
    return findings


# --------------------------------------------------------------------------
# Exercise: populate PROGRAM_AUDIT with a tiny Q5-shaped pipeline


def exercise_programs(n_events: int = 4096, batch: int = 1024,
                      capacity: int = 2048,
                      fire_modes: Tuple[str, ...] = ("full",
                                                     "incremental"),
                      ) -> List[str]:
    """Run a tiny Q5 sliding-window job (per fire mode) so every
    window-path builder registers its compiled programs in
    PROGRAM_AUDIT; returns the registered scopes.  Mirrors bench.py
    _run_q5 at toy scale — same operators, same program builders.

    The device-time ledger records through the same runs (restored to
    its prior enablement on return), so the audit doubles as a drill of
    every ledger-wrapped dispatch site: the TPU305 inventory can be
    checked against scopes that actually fired, not just grep hits."""
    import numpy as np

    from flink_tpu.api import StreamExecutionEnvironment
    from flink_tpu.core import WatermarkStrategy
    from flink_tpu.core.config import PipelineOptions
    from flink_tpu.metrics.device import PROGRAM_AUDIT
    from flink_tpu.metrics.profiler import DEVICE_LEDGER
    from flink_tpu.core.records import Schema
    from flink_tpu.runtime.operators.device_window import AggSpec
    from flink_tpu.window import SlidingEventTimeWindows

    ledger_was_enabled = DEVICE_LEDGER.enabled
    DEVICE_LEDGER.enabled = True
    try:
        schema = Schema([("auction", np.int64), ("price", np.int64),
                         ("ts", np.int64)])
        pane_ms = 2000
        n_panes = max(2, n_events // batch)
        span = n_panes * pane_ms

        def gen(idx):
            u = idx.astype(np.uint64)
            return {"auction": ((u * np.uint64(2654435761)) % np.uint64(64))
                    .astype(np.int64),
                    "price": (idx % 97) + 1,
                    "ts": (idx * span) // n_events}

        from flink_tpu.core.functions import SinkFunction

        class _DiscardSink(SinkFunction):
            def invoke_batch(self, batch):
                return True

        # (fire_mode, device_ingest, fused): device ingest exercises the
        # coalesced native_fold program, host ingest the per-batch step
        # program, and the fused run registers the certified chain programs
        # (chain.fused_prelude / chain.fused_step) for JX601-603.
        runs = ([(m, True, False) for m in fire_modes]
                + [(fire_modes[0], False, False), (fire_modes[0], True, True)])
        for fire_mode, device_ingest, fused in runs:
            env = StreamExecutionEnvironment.get_execution_environment()
            env.set_state_backend("tpu")
            env.config.set(PipelineOptions.BATCH_SIZE, batch)
            env.config.set(PipelineOptions.FUSION, fused)
            env.config.set("window.fire.incremental",
                           fire_mode == "incremental")
            ws = WatermarkStrategy.for_monotonous_timestamps() \
                .with_timestamp_column("ts")
            (env.datagen(gen, schema, count=n_events, timestamp_column="ts",
                         watermark_strategy=ws, device=device_ingest)
                .key_by("auction")
                .window(SlidingEventTimeWindows.of(3 * pane_ms, pane_ms))
                .device_aggregate(
                    [AggSpec("count", out_name="bids", value_bits=31),
                     AggSpec("sum", "price", out_name="revenue")],
                    capacity=capacity, ring_size=16, emit_window_bounds=False,
                    emit_topk=32, defer_overflow=True)
                .add_sink(_DiscardSink(), "audit-sink"))
            env.execute(f"tpu-lint-audit-{fire_mode}", timeout=600.0)

        # sharded (mesh.*) programs: one direct step + fused fire on a tiny
        # ShardedWindowAgg so the JX505 local-key audit has entries to lint
        import jax
        import jax.numpy as jnp

        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_window import AggDef, ShardedWindowAgg

        D = max(1, min(4, len(jax.devices())))
        agg = ShardedWindowAgg(make_mesh(D),
                               [AggDef("price", "sum", jnp.int64)],
                               capacity=256, ring=8, max_parallelism=128)
        state = agg.init_state()
        B = 64
        keys = (jnp.arange(D * B, dtype=jnp.int64) % 37).reshape(D, B) + 1
        state, _ = agg.step(state, keys,
                            {"price": jnp.ones((D, B), jnp.int64)},
                            jnp.zeros((D, B), jnp.int32),
                            jnp.ones((D, B), bool))
        agg.fire_compact(state, np.arange(4), np.ones(4, bool),
                         "price", 8)
        return sorted({e.scope for e in PROGRAM_AUDIT})
    finally:
        DEVICE_LEDGER.enabled = ledger_was_enabled
