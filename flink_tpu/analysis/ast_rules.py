"""Tier-A AST rules: host-sync discipline, singleton wiring, lock
discipline, and determinism.  Pure stdlib ``ast`` — no jax import.

Rule ids follow TPU<family><n>: 1xx device/host boundary, 2xx wiring,
4xx concurrency, 5xx determinism (3xx inventory rules live in
inventory.py, JX5xx jaxpr rules in jaxpr_rules.py).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, rule

# --------------------------------------------------------------------------
# Shared AST helpers


def _walk_with_qualname(tree: ast.Module):
    """Yield (node, qualname_of_enclosing_def) for every node, where
    qualname is e.g. 'Class.method' ('<module>' at module level).
    Nested defs (closures inside a method) keep the OUTER def's qualname
    suffix chain so findings anchor to a greppable symbol."""
    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                yield child, ".".join(stack) or "<module>"
                yield from visit(child, stack + [child.name])
            else:
                yield child, ".".join(stack) or "<module>"
                yield from visit(child, stack)

    yield from visit(tree, [])


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_snippet(ctx: AnalysisContext, rel: str, node: ast.AST) -> str:
    seg = ast.get_source_segment(ctx.source(rel), node) or ""
    return " ".join(seg.split())[:120]


# --------------------------------------------------------------------------
# TPU101 — host-sync in hot-path modules

_SYNC_WRAPPERS = {"float", "int", "bool"}
_SYNC_NP_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_DEVICE_TOKEN = re.compile(r"(^|_)dev(ice)?(_|$|s$)")


def _mentions_device_value(node: ast.AST) -> bool:
    """Heuristic: does any identifier in this expression look like a
    device-resident value (…_dev, device_…, …_device, devices)?  Plain
    host numpy locals ('ts', 'counts', …) do not match, which keeps
    int(ts.min()) on host arrays out of scope."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name and _DEVICE_TOKEN.search(name):
            return True
    return False


@rule("TPU101", "host-sync in hot path", "A",
      "float()/int()/bool()/.item()/np.asarray() on a device value "
      "inside a hot-path module forces a device->host sync per call "
      "(the PR 8 late_dropped-per-scrape bug class); annotate "
      "deliberate syncs with '# lint: sync-ok <reason>'")
def host_sync_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.settings.hot_path_modules:
        rel = ctx.pkg_rel(mod)
        try:
            tree = ctx.tree(rel)
        except FileNotFoundError:
            continue
        flagged = []
        for node, qual in _walk_with_qualname(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            is_sync = False
            what = ""
            if dotted in ("jax.device_get",) or (
                    dotted and dotted.endswith(".device_get")):
                is_sync, what = True, "jax.device_get"
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args):
                is_sync, what = True, ".item()"
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in _SYNC_WRAPPERS and node.args
                  and _mentions_device_value(node.args[0])):
                is_sync, what = True, f"{node.func.id}()"
            elif (dotted in _SYNC_NP_FUNCS and node.args
                  and _mentions_device_value(node.args[0])):
                is_sync, what = True, dotted
            if is_sync:
                flagged.append((node, qual, what))
        # int(jax.device_get(x)) is ONE sync: report the outermost call
        # only, not the nested device_get a second time.
        inner = set()
        for node, _q, _w in flagged:
            for sub in ast.walk(node):
                if sub is not node and isinstance(sub, ast.Call):
                    inner.add(id(sub))
        for node, qual, what in flagged:
            if id(node) in inner:
                continue
            if ctx.suppression(rel, node.lineno, "sync-ok"):
                continue
            snippet = _call_snippet(ctx, rel, node)
            findings.append(Finding(
                rule="TPU101", file=rel, line=node.lineno,
                symbol=f"{qual}:{snippet}",
                message=f"{what} on a device value in hot-path module "
                        f"({snippet})",
                hint="keep the value on device (jnp ops / device "
                     "accumulators) or, if this sync is deliberate and "
                     "amortized, annotate '# lint: sync-ok <reason>'"))
    return findings


# --------------------------------------------------------------------------
# TPU102 — collectives must name a declared mesh axis

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "psum_scatter",
                "all_gather", "all_to_all", "ppermute", "pshuffle",
                "axis_index", "axis_size"}
# axis is the sole/first argument for these; everything else takes it
# second (after the operand)
_AXIS_ARG0 = {"axis_index", "axis_size"}


def _declared_axes(ctx: AnalysisContext) -> Set[str]:
    """Statically resolve parallel/plan.py DECLARED_AXES without importing
    the package (Tier A stays jax-free): string constants are taken as-is,
    names resolve against parallel/mesh.py module-level string assigns."""
    axes: Set[str] = set()
    consts: Dict[str, str] = {}
    try:
        for node in ctx.tree(ctx.pkg_rel("parallel/mesh.py")).body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                consts[node.targets[0].id] = node.value.value
        for node in ctx.tree(ctx.pkg_rel("parallel/plan.py")).body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "DECLARED_AXES"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        axes.add(elt.value)
                    elif isinstance(elt, ast.Name) and elt.id in consts:
                        axes.add(consts[elt.id])
    except FileNotFoundError:
        pass
    return axes


def _axis_expr_ok(node: ast.AST, axes: Set[str]) -> bool:
    """Is this axis argument provably one of the declared axes?  Accepted:
    a matching string literal, the DATA_AXIS constant, or an identifier /
    attribute named ``axis_name`` (the plan threads the declared axis
    under exactly that name)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and node.value in axes
    if isinstance(node, ast.Name):
        return node.id in ("DATA_AXIS", "axis_name")
    if isinstance(node, ast.Attribute):
        return node.attr in ("axis_name", "DATA_AXIS")
    return False


@rule("TPU102", "collective over an undeclared mesh axis", "A",
      "every lax collective (psum/all_to_all/ppermute/...) must name an "
      "axis from parallel/plan.py DECLARED_AXES — a collective over an "
      "ad-hoc axis string either fails at trace time on a real mesh or "
      "silently reduces over the wrong dimension after a mesh reshape; "
      "annotate exceptions with '# lint: axis-ok <reason>'")
def declared_axis_rule(ctx: AnalysisContext) -> List[Finding]:
    axes = _declared_axes(ctx)
    findings: List[Finding] = []
    for rel in ctx.package_files():
        try:
            tree = ctx.tree(rel)
        except (FileNotFoundError, SyntaxError):
            continue
        for node, qual in _walk_with_qualname(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            parts = dotted.split(".")
            if parts[-1] not in _COLLECTIVES:
                continue
            # only lax collectives: lax.psum / jax.lax.psum; a local
            # helper that happens to be called psum is out of scope
            if len(parts) < 2 or parts[-2] != "lax":
                continue
            fn = parts[-1]
            axis_arg = None
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis_arg = kw.value
            if axis_arg is None:
                pos = 0 if fn in _AXIS_ARG0 else 1
                if len(node.args) > pos:
                    axis_arg = node.args[pos]
            if axis_arg is not None and _axis_expr_ok(axis_arg, axes):
                continue
            if ctx.suppression(rel, node.lineno, "axis-ok"):
                continue
            snippet = _call_snippet(ctx, rel, node)
            findings.append(Finding(
                rule="TPU102", file=rel, line=node.lineno,
                symbol=f"{qual}:{snippet}",
                message=f"collective {fn} does not name a declared mesh "
                        f"axis ({snippet}); declared: "
                        f"{sorted(axes) or '<none resolved>'}",
                hint="pass the plan's axis (DATA_AXIS / a threaded "
                     "axis_name) or add the axis to parallel/plan.py "
                     "DECLARED_AXES first; annotate deliberate exceptions "
                     "'# lint: axis-ok <reason>'"))
    return findings


# --------------------------------------------------------------------------
# TPU201 — singleton wiring on deploy entry points


class _ModuleIndex:
    """Per-module call-graph facts: function/method bodies, the
    singletons each configures, the local+imported callees each calls."""

    def __init__(self, ctx: AnalysisContext, rel: str):
        self.rel = rel
        self.defs: Dict[str, ast.AST] = {}           # qualname -> def node
        self.class_methods: Dict[str, List[str]] = {}
        self.imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod_rel, name)
        tree = ctx.tree(rel)
        pkg = ctx.package_name
        # import resolution: `from .local import deploy_local` etc.
        mod_dir = "/".join(rel.split("/")[:-1])
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.level >= 0:
                target = self._resolve_from(ctx, rel, mod_dir, node, pkg)
                if target:
                    for alias in node.names:
                        self.imports[alias.asname or alias.name] = (
                            target, alias.name)
        for node, qual in _walk_with_qualname(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{qual}.{node.name}" if qual != "<module>" \
                    else node.name
                self.defs[name] = node
                if qual != "<module>" and "." not in qual:
                    self.class_methods.setdefault(qual, []).append(name)

    @staticmethod
    def _resolve_from(ctx, rel, mod_dir, node: ast.ImportFrom, pkg):
        """Best-effort: map an import-from to a repo-relative module
        path inside the package (None for stdlib / external)."""
        if node.level:  # relative import
            base = rel.split("/")[:-1]
            up = node.level - 1
            if up:
                base = base[:-up] if up <= len(base) else []
            mod = node.module.split(".") if node.module else []
            parts = base + mod
        else:
            if not node.module or not node.module.startswith(pkg):
                return None
            parts = node.module.split(".")
        cand = "/".join(parts) + ".py"
        if (ctx.root / cand).is_file():
            return cand
        cand = "/".join(parts) + "/__init__.py"
        if (ctx.root / cand).is_file():
            return cand
        return None


def _fn_facts(fn_node: ast.AST) -> Tuple[Set[str], Set[str], Set[str]]:
    """(configured_names, called_locals, called_self_methods) within one
    def body (nested defs included — closures run on behalf of the
    caller)."""
    configured: Set[str] = set()
    called: Set[str] = set()
    self_calls: Set[str] = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "configure":
                base = _dotted(f.value)
                if base:
                    configured.add(base.split(".")[-1])
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                self_calls.add(f.attr)
            else:
                called.add(f.attr)
        elif isinstance(f, ast.Name):
            called.add(f.id)
    return configured, called, self_calls


@rule("TPU201", "deploy path misses a singleton configure", "A",
      "every deploy entry point must (transitively) call "
      "X.configure(config) for each registered process-global — an "
      "unwired FAULTS/WATCHDOG/TRACER/FLIGHT_RECORDER silently degrades "
      "fault injection, stall supervision, and tracing")
def singleton_wiring_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    indexes: Dict[str, _ModuleIndex] = {}

    def index(rel: str) -> Optional[_ModuleIndex]:
        if rel not in indexes:
            try:
                indexes[rel] = _ModuleIndex(ctx, rel)
            except FileNotFoundError:
                return None
        return indexes[rel]

    def reachable_configured(rel: str, qual: str,
                             seen: Set[Tuple[str, str]]) -> Set[str]:
        """BFS over the package-local call graph from (module, qualname)
        collecting every singleton name whose .configure() is called."""
        out: Set[str] = set()
        work = [(rel, qual)]
        while work:
            mrel, q = work.pop()
            if (mrel, q) in seen:
                continue
            seen.add((mrel, q))
            idx = index(mrel)
            if idx is None:
                continue
            # A class entry point means the union over its methods.
            if q in idx.class_methods:
                for meth in idx.class_methods[q]:
                    work.append((mrel, meth))
                continue
            fn = idx.defs.get(q)
            if fn is None:
                continue
            configured, called, self_calls = _fn_facts(fn)
            out |= configured
            cls = q.split(".")[0] if "." in q else None
            for meth in self_calls:
                if cls and f"{cls}.{meth}" in idx.defs:
                    work.append((mrel, f"{cls}.{meth}"))
            for name in called:
                if name in idx.defs:
                    work.append((mrel, name))
                elif name in idx.imports:
                    tgt_rel, tgt_name = idx.imports[name]
                    work.append((tgt_rel, tgt_name))
        return out

    for mod, qual in ctx.settings.entry_points:
        rel = ctx.pkg_rel(mod)
        idx = index(rel)
        if idx is None or (qual not in idx.defs
                           and qual not in idx.class_methods):
            findings.append(Finding(
                rule="TPU201", file=rel, line=0, symbol=qual,
                message=f"declared deploy entry point {qual} not found",
                hint="update AnalysisSettings.entry_points"))
            continue
        configured = reachable_configured(rel, qual, set())
        node = idx.defs.get(qual)
        line = getattr(node, "lineno", 0) if node else 0
        if not line and qual in idx.class_methods:
            for n in ast.walk(ctx.tree(rel)):
                if isinstance(n, ast.ClassDef) and n.name == qual:
                    line = n.lineno
                    break
        for singleton, accepted in ctx.settings.singletons:
            if not any(a in configured for a in accepted):
                findings.append(Finding(
                    rule="TPU201", file=rel, line=line,
                    symbol=f"{qual}:{singleton}",
                    message=f"deploy entry point {qual} never configures "
                            f"{singleton} (accepted via "
                            f"{'/'.join(accepted)}.configure)",
                    hint=f"call {accepted[0]}.configure(config) on this "
                         "deploy path (see cluster/local.py deploy_local "
                         "for the canonical wiring block)"))
    return findings


# --------------------------------------------------------------------------
# TPU401 — lock discipline on classes owning _lock

_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "clear",
    "update", "add", "discard", "appendleft", "setdefault",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    """self.X -> 'X'; self.X[...] -> 'X'; else None."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _class_lock_findings(ctx: AnalysisContext, rel: str,
                         cls: ast.ClassDef) -> List[Finding]:
    owns_lock = False
    for node in ast.walk(cls):
        attr = None
        if isinstance(node, ast.Assign) and node.targets:
            attr = _self_attr(node.targets[0])
        if attr == "_lock":
            owns_lock = True
            break
    if not owns_lock:
        return []

    # Pass 1: which attrs does this class EVER mutate under the lock?
    # Only those are treated as lock-protected; attrs that are never
    # guarded anywhere (init-once config, etc.) stay out of scope, which
    # keeps the rule precise instead of flagging every assignment.
    guarded: Set[str] = set()
    mutations: List[Tuple[str, int, str, bool]] = []  # attr, line, meth, locked

    def scan(node, in_lock: bool, meth: str):
        if isinstance(node, ast.With):
            locked = in_lock or any(
                (_dotted(item.context_expr) or "").endswith("._lock")
                or (isinstance(item.context_expr, ast.Call)
                    and (_dotted(item.context_expr.func) or "")
                    .endswith("._lock"))
                for item in node.items)
            for child in node.body:
                scan(child, locked, meth)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested def: a closure body runs later, possibly without
            # the lock — treat it as its own (unlocked) scope.
            for child in ast.iter_child_nodes(node):
                scan(child, False, meth)
            return
        attr = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr:
                    mutations.append((attr, node.lineno, meth, in_lock))
                    if in_lock:
                        guarded.add(attr)
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in _MUTATING_METHODS):
                attr = _self_attr(f.value)
                if attr:
                    mutations.append((attr, node.lineno, meth, in_lock))
                    if in_lock:
                        guarded.add(attr)
        for child in ast.iter_child_nodes(node):
            scan(child, in_lock, meth)

    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # the `_locked` suffix is the caller-holds-the-lock
            # convention (e.g. _verified_candidate_locked)
            held = item.name.endswith("_locked")
            for child in item.body:
                scan(child, held, item.name)

    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for attr, line, meth, locked in mutations:
        if locked or attr not in guarded or attr == "_lock":
            continue
        if meth == "__init__":
            continue  # construction happens-before publication
        if (attr, line) in seen:
            continue
        seen.add((attr, line))
        if ctx.suppression(rel, line, "lock-ok"):
            continue
        findings.append(Finding(
            rule="TPU401", file=rel, line=line,
            symbol=f"{cls.name}.{meth}:{attr}",
            message=f"{cls.name}.{meth} mutates self.{attr} outside "
                    f"'with self._lock' but the class guards that attr "
                    f"elsewhere",
            hint="move the mutation under the lock, or annotate "
                 "'# lint: lock-ok <reason>' if single-threaded by "
                 "construction"))
    return findings


@rule("TPU401", "un-locked mutation in a lock-owning class", "A",
      "classes that own a _lock must mutate their lock-guarded "
      "attributes under 'with self._lock' — a torn read on the scrape "
      "or checkpoint path is silent corruption")
def lock_discipline_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel in ctx.package_files():
        try:
            tree = ctx.tree(rel)
        except (FileNotFoundError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_class_lock_findings(ctx, rel, node))
    return findings


# --------------------------------------------------------------------------
# TPU402 — module-level mutable containers need a guard annotation

_CONTAINER_CALLS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}


@rule("TPU402", "unguarded module-level mutable container", "A",
      "a module-level dict/list/set/deque mutated from more than one "
      "function is cross-thread shared state; it needs a lock or an "
      "explicit '# lint: guarded-by <reason>' annotation at its "
      "definition")
def global_guard_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel in ctx.package_files():
        try:
            tree = ctx.tree(rel)
        except (FileNotFoundError, SyntaxError):
            continue
        # module-level containers
        containers: Dict[str, int] = {}
        for node in tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            v = node.value
            is_container = (
                isinstance(v, (ast.Dict, ast.List, ast.Set))
                or (isinstance(v, ast.Call)
                    and (_dotted(v.func) or "").split(".")[-1]
                    in _CONTAINER_CALLS))
            if is_container:
                containers[tgt.id] = node.lineno
        if not containers:
            continue
        # functions that mutate each container (module-level decorator
        # registration at import time is single-threaded and exempt)
        mutators: Dict[str, Set[str]] = {name: set() for name in containers}
        for node, qual in _walk_with_qualname(tree):
            if qual == "<module>":
                continue
            name = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name):
                        name = t.value.id
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _MUTATING_METHODS
                        and isinstance(f.value, ast.Name)):
                    name = f.value.id
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name):
                        name = t.value.id
            if name in mutators:
                mutators[name].add(qual)
        for name, funcs in mutators.items():
            if len(funcs) < 2:
                continue
            line = containers[name]
            if ctx.suppression(rel, line, "guarded-by"):
                continue
            findings.append(Finding(
                rule="TPU402", file=rel, line=line, symbol=name,
                message=f"module-level container {name} is mutated from "
                        f"{len(funcs)} functions "
                        f"({', '.join(sorted(funcs)[:4])}) with no guard "
                        "annotation",
                hint="protect it with a lock or annotate the definition "
                     "'# lint: guarded-by <reason>' (e.g. GIL-atomic "
                     "deque ops, import-time only)"))
    return findings


# --------------------------------------------------------------------------
# TPU501 — wall clock in span/tracing paths


@rule("TPU501", "time.time() in a span/tracing module", "A",
      "span timestamps must come from the monotonic-anchored clock "
      "(now_ms in metrics/tracing.py) so traces stay ordered under NTP "
      "steps; raw time.time() breaks cross-host span ordering")
def wall_clock_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.settings.span_clock_modules:
        rel = ctx.pkg_rel(mod)
        try:
            tree = ctx.tree(rel)
        except FileNotFoundError:
            continue
        for node, qual in _walk_with_qualname(tree):
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func) != "time.time":
                continue
            if ctx.suppression(rel, node.lineno, "wall-clock-ok"):
                continue
            findings.append(Finding(
                rule="TPU501", file=rel, line=node.lineno,
                symbol=f"{qual}:time.time",
                message=f"time.time() in span path ({qual})",
                hint="use now_ms() (monotonic-anchored) from "
                     "flink_tpu.metrics.tracing, or annotate "
                     "'# lint: wall-clock-ok <reason>'"))
    return findings


# --------------------------------------------------------------------------
# TPU502 — unseeded RNG in runtime modules

_NP_RANDOM_OK = {"default_rng", "Generator", "SeedSequence", "PCG64"}


@rule("TPU502", "unseeded RNG in a runtime module", "A",
      "fault schedules, backoff jitter, and recovery paths must be "
      "replayable from a seed; module-level random.* / np.random.* "
      "calls and bare random.Random() are not")
def unseeded_rng_rule(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    prefixes = tuple(ctx.pkg_rel(p) for p in
                     ctx.settings.runtime_rng_prefixes)
    for rel in ctx.package_files():
        if not rel.startswith(prefixes):
            continue
        try:
            tree = ctx.tree(rel)
        except (FileNotFoundError, SyntaxError):
            continue
        for node, qual in _walk_with_qualname(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            bad = None
            if dotted.startswith("random.") and dotted != "random.Random":
                bad = dotted
            elif dotted == "random.Random" and not node.args:
                bad = "random.Random()  (no seed)"
            elif (dotted.startswith(("np.random.", "numpy.random."))
                  and dotted.split(".")[-1] not in _NP_RANDOM_OK):
                bad = dotted
            elif (dotted.split(".")[-1] == "default_rng"
                  and "random" in dotted and not node.args):
                bad = f"{dotted}()  (no seed)"
            if bad is None:
                continue
            if ctx.suppression(rel, node.lineno, "rng-ok"):
                continue
            findings.append(Finding(
                rule="TPU502", file=rel, line=node.lineno,
                symbol=f"{qual}:{bad}",
                message=f"unseeded RNG {bad} in runtime module ({qual})",
                hint="thread a seeded random.Random(seed) / "
                     "np.random.default_rng(seed) through the config, or "
                     "annotate '# lint: rng-ok <reason>'"))
    return findings
