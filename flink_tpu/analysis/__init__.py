"""tpu-lint: static analysis for the device-path invariants.

Two tiers (SURVEY.md §7 "enforce by machine, not convention"):

* Tier A — AST passes over the whole package: host-sync discipline in
  hot-path modules, singleton wiring on deploy entry points, inventory
  locks (spans / fault sites / config keys vs. code and docs), lock
  discipline, determinism (wall-clock + RNG).
* Tier B — jaxpr program audit: abstractly re-trace every compiled-
  segment builder registered through ``instrumented_program_cache`` and
  lint the program IR itself (scatter lowering on the fire path, f64
  leaks, missing donation, value-derived cache keys).

Findings carry file:line + rule id + fix hint and diff against the
committed ``flink_tpu/analysis/baseline.json``; any unbaselined finding
fails the tier-1 ``lint``-marked test (tests/test_analysis.py) and the
``python -m flink_tpu.cli lint`` subcommand.

See docs/ANALYSIS.md for the rule catalogue and suppression syntax.
"""

from .core import (  # noqa: F401
    AnalysisContext,
    Finding,
    Rule,
    all_rules,
    baseline_path,
    diff_against_baseline,
    load_baseline,
    run_rules,
    rule,
    save_baseline,
)

# Importing the rule modules registers their rules.
from . import ast_rules  # noqa: F401,E402
from . import inventory  # noqa: F401,E402
from . import jaxpr_rules  # noqa: F401,E402
from . import plan_rules  # noqa: F401,E402
