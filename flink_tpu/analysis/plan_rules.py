"""Tier-P (plan) rules: graph-level fusion findings from the fusion
certifier, plus the baseline-hygiene rule.

The certifier (graph/fusion.py) does the actual analysis when a job
graph is compiled; these rules surface its rejected-boundary findings
through the tpu-lint gate so an example or test pipeline that SHOULD
fuse — but is cut by a host-effectful op, a serializer boundary, a
shuffle, or a timer escape — fails ``pytest -m lint`` against the
committed baseline like any other regression.

Certificates come from ``fusion.CERTIFICATE_LOG`` (populated by every
``certify()`` call in-process — tests seed it directly); when the log
is empty the rules certify every pipeline under ``examples/`` through
the capture harness, mirroring how Tier B exercises device programs.
"""

from __future__ import annotations

from typing import List

from .core import AnalysisContext, Finding, load_baseline, rule, skip_rule

__all__ = ["plan_rule_ids"]

_PLAN_RULES = ("PLAN601", "PLAN602", "PLAN603", "PLAN604")


def plan_rule_ids() -> tuple:
    return _PLAN_RULES


def _certificates(ctx: AnalysisContext) -> list:
    cached = getattr(ctx, "_plan_certificates", None)
    if cached is not None:
        return cached
    try:
        from ..graph.fusion import CERTIFICATE_LOG, exercise_certificates
    except Exception as e:  # pragma: no cover - broken runtime import
        skip_rule(f"fusion certifier unavailable: {e!r}")
    certs = list(CERTIFICATE_LOG)
    if not certs:
        try:
            certs = exercise_certificates(ctx.root / "examples")
        except Exception as e:
            skip_rule(f"could not exercise example pipelines: {e!r}")
    if not certs:
        skip_rule("no fusion certificates captured "
                  "(no pipelines compiled, no examples/ found)")
    ctx._plan_certificates = certs
    return certs


def _plan_findings(ctx: AnalysisContext, rule_id: str,
                   hint: str) -> List[Finding]:
    out: List[Finding] = []
    seen = set()
    for cert in _certificates(ctx):
        for f in cert.findings():
            if f.rule != rule_id:
                continue
            key = (f.file, f.line, f.symbol, f.message)
            if key in seen:
                continue
            seen.add(key)
            if f.file != "<unknown>" and f.line and \
                    (ctx.root / f.file).is_file() and \
                    ctx.suppression(f.file, f.line, rule_id.lower()):
                continue
            out.append(Finding(rule=rule_id, file=f.file, line=f.line,
                               symbol=f.symbol, message=f.message,
                               hint=hint))
    return out


@rule("PLAN601", "host-effectful op cuts a fusable chain", "P",
      "An opaque/host-effectful operator interrupts a run of "
      "device-fusable operators: every record pays a device round-trip "
      "plus a dispatch at the boundary.")
def plan601_host_effectful(ctx: AnalysisContext) -> List[Finding]:
    return _plan_findings(
        ctx, "PLAN601",
        "make the op jax-traceable (BatchFn(traceable=True) / a "
        "vectorized *_batch method) or move it past the flush point")


@rule("PLAN602", "serializer/schema boundary cuts a fusable chain", "P",
      "A row-loop operator decodes host rows in the middle of a "
      "device-fusable run — a serialize/deserialize boundary that "
      "forces device->host materialization per batch.")
def plan602_serializer(ctx: AnalysisContext) -> List[Finding]:
    return _plan_findings(
        ctx, "PLAN602",
        "implement map_batch/filter_batch so the op stays columnar, or "
        "hoist the row logic behind the keyed flush point")


@rule("PLAN603", "shuffle where fusion was possible", "P",
      "A non-forward (or feedback) exchange separates two fusable "
      "operators at equal parallelism: the shuffle costs a dispatch + "
      "partition round-trip a forward edge would not.")
def plan603_shuffle(ctx: AnalysisContext) -> List[Finding]:
    return _plan_findings(
        ctx, "PLAN603",
        "drop the rebalance/rescale between pure operators (forward "
        "edges chain) or move the keyed exchange to the stateful op")


@rule("PLAN604", "timer/side-output escape cuts a fusable chain", "P",
      "A timer-driven operator or a side-output tag escapes the "
      "candidate fused region: records/timers leave mid-dispatch, so "
      "the chain cannot lower to one program across it.")
def plan604_escape(ctx: AnalysisContext) -> List[Finding]:
    return _plan_findings(
        ctx, "PLAN604",
        "timers and side outputs are legal only at chain flush points; "
        "split the chain there or fold the logic into the window step")


@rule("BASE601", "baseline entry still carries the TODO reason", "A",
      "Every committed baseline entry must carry a reviewed reason; "
      "'TODO: justify this exception or fix it' is the placeholder "
      "--update-baseline stamps when --reason was not given.")
def base601_todo_reason(ctx: AnalysisContext) -> List[Finding]:
    out: List[Finding] = []
    for e in load_baseline():
        reason = (e.get("reason") or "").strip()
        if not reason or reason.startswith("TODO"):
            out.append(Finding(
                rule="BASE601",
                file="flink_tpu/analysis/baseline.json", line=0,
                symbol=e.get("fingerprint", "?"),
                message=(f"baseline entry {e.get('rule')} @ "
                         f"{e.get('file')}:{e.get('symbol')} has no "
                         f"reviewed reason (got {reason!r})"),
                hint="re-run cli lint --update-baseline --reason '<why "
                     "this exception is sound>' or fix the finding"))
    return out
